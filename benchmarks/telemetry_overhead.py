"""Telemetry overhead benchmark: what the stage spans cost the hot path.

The round-14 telemetry plane (p1_tpu/node/telemetry.py) instruments the
block pipeline — wire frame -> admission -> validation -> store append ->
relay — as clock-seam spans.  Observability that slows the system it
observes is a tax nobody audited, so this harness measures exactly that:
the SAME block stream driven through a real ``Node``'s ``_dispatch``
front door (decode, governor admission, add_block, store append, relay
encode — everything a gossip frame pays) with telemetry enabled and
disabled, best-of-N each, on one JSON line.

It also emits the per-stage latency table (p50/p95/p99 from the enabled
run's histograms) — the figure docs/PERF.md's "Telemetry plane" section
records from a 10k-block run, and the ROADMAP-2 pipeline split will be
scoped against.

Same contract as bench.py: measured on this machine, no estimates.
Difficulty 1 keeps mining the fixtures cheap while the PoW checks stay
real; signature memos are warmed first (the mempool-admission state a
steady-state block meets), so the measured plane is
serialization/validation/bookkeeping, not Ed25519.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

# Runnable as `python benchmarks/telemetry_overhead.py` from a checkout.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


class _BenchPeer:
    """The minimal peer surface ``_dispatch``/``_handle_block`` touch on
    the ingest path: a label, a host, and a real governor budget."""

    label = "bench"
    host = "127.0.0.1"
    mempool_inflight_since = None

    def __init__(self, node):
        self.budget = node.governor.budget()


def _build_frames(n_blocks: int, txs: int, difficulty: int):
    """Wire BLOCK frames (unstamped) for a freshly mined chain, plus the
    chain itself so callers can warm signature memos."""
    from benchmarks.host_ingest import build_blocks

    from p1_tpu.node import protocol
    from p1_tpu.core.block import Block

    chain, raws = build_blocks(n_blocks, txs, difficulty)
    frames = [
        protocol.encode_block(Block.deserialize(raw)) for raw in raws
    ]
    return chain, frames


_ROUND = 0


def _make_node(blocks, difficulty: int, telemetry: bool, tmpdir):
    """A fresh node over a fresh on-disk store, its verify-once
    signature cache seeded with the fixture chain's (known-valid — we
    mined it) signatures: a steady-state node has already verified
    every transfer a block carries at mempool admission, and this
    harness measures the serialization/validation/bookkeeping plane
    (host_ingest.py's contract), not cold Ed25519 — which on the
    wheel-less host would drown the span overhead it exists to
    expose."""
    global _ROUND
    from p1_tpu.chain.store import ChainStore
    from p1_tpu.config import NodeConfig
    from p1_tpu.node.node import Node

    _ROUND += 1
    store = ChainStore(Path(tmpdir) / f"tel_{_ROUND}.chain", fsync=False)
    node = Node(
        NodeConfig(
            difficulty=difficulty,
            mine=False,
            mempool_ttl_s=0.0,
            telemetry=telemetry,
        ),
        store=store,
    )
    for blk in blocks:
        for tx in blk.txs:
            if not tx.is_coinbase:
                node.sig_cache.add(tx.txid(), tx.pubkey, tx.sig)
    store.acquire()
    return node


def paired_round(frames, blocks, difficulty: int, tmpdir):
    """One pass of the block stream through TWO nodes — telemetry off
    and on — dispatching each frame to both back to back, per-frame
    timed, the first-dispatcher alternating per frame.

    Why this shape: on this host identical whole-stream rounds swing
    ±20% (CPU-quota throttling oscillates at the same timescale as a
    round), so any round-level A/B measures the environment, not the
    spans — the round-14 ledger records two failed cuts.  Frame-level
    interleaving puts both variants microseconds apart inside every
    throttle window, and alternating who goes first cancels the
    cache-warming the first dispatcher does for the second (both nodes
    decode the same frame bytes).  Returns (bps_off, bps_on, node_on).
    """
    node_off = _make_node(blocks, difficulty, False, tmpdir)
    node_on = _make_node(blocks, difficulty, True, tmpdir)

    async def _run():
        peer_off = _BenchPeer(node_off)
        peer_on = _BenchPeer(node_on)
        dts_off = []
        dts_on = []
        perf = time.perf_counter
        for i, frame in enumerate(frames):
            if i % 2 == 0:
                a = perf()
                await node_off._dispatch(peer_off, frame)
                b = perf()
                await node_on._dispatch(peer_on, frame)
                c = perf()
                dts_off.append(b - a)
                dts_on.append(c - b)
            else:
                a = perf()
                await node_on._dispatch(peer_on, frame)
                b = perf()
                await node_off._dispatch(peer_off, frame)
                c = perf()
                dts_on.append(b - a)
                dts_off.append(c - b)
        return dts_off, dts_on

    try:
        dts_off, dts_on = asyncio.run(_run())
    finally:
        node_off.store.close()
        node_on.store.close()
    for node in (node_off, node_on):
        assert node.chain.height == len(frames), (
            node.chain.height,
            len(frames),
        )
    # Medians, not sums: a handful of kernel-writeback (or throttle)
    # stalls land on random frames and would skew a sum by whole
    # percents; the per-frame median is immune to them, and the paired
    # per-frame DIFFERENCE median cancels content variation too.
    dts_off.sort()
    dts_on.sort()
    med_off = dts_off[len(dts_off) // 2]
    med_on = dts_on[len(dts_on) // 2]
    return 1.0 / med_off, 1.0 / med_on, node_on


def _stage_table(node) -> dict:
    """{stage: {count, p50_ms, p95_ms, p99_ms}} from the node's
    registry — the PERF.md per-stage latency rows."""
    out = {}
    for name in (
        "stage.frame_s",
        "stage.admission_s",
        "stage.validate_s",
        "stage.store_s",
        "stage.relay_s",
    ):
        h = node.telemetry.histograms.get(name)
        if h is None or h.count == 0:
            continue
        out[name] = {
            "count": h.count,
            "p50_ms": round(1e3 * h.percentile(50), 4),
            "p95_ms": round(1e3 * h.percentile(95), 4),
            "p99_ms": round(1e3 * h.percentile(99), 4),
        }
    return out


def bench_quick(blocks: int = 300, txs: int = 2, repeats: int = 3) -> dict:
    """The bench.py entry: small run, same shape as main()'s output.

    One discarded warmup round, then ``repeats`` frame-interleaved
    paired rounds (see ``paired_round`` for why round-level A/B is
    unmeasurable on this host); the overhead figure is the median of
    the per-round on/off ratios."""
    difficulty = 1
    chain, frames = _build_frames(blocks, txs, difficulty)
    # main_chain() yields lazily — materialize, or the first seeding
    # pass would exhaust it and every later node would run cache-cold.
    fixture_blocks = list(chain.main_chain())
    ratios = []
    bps_off = bps_on = 0.0
    node = None
    with tempfile.TemporaryDirectory() as tmpdir:
        paired_round(frames, fixture_blocks, difficulty, tmpdir)  # warmup
        for _ in range(repeats):
            off, on, node = paired_round(
                frames, fixture_blocks, difficulty, tmpdir
            )
            ratios.append(on / off)
            bps_off = max(bps_off, off)
            bps_on = max(bps_on, on)
    ratios.sort()
    median_ratio = ratios[len(ratios) // 2]
    return {
        "ingest_plain_bps": round(bps_off, 1),
        "ingest_telemetry_bps": round(bps_on, 1),
        "overhead_pct": round(100.0 * (1.0 - median_ratio), 2),
        "pair_ratios": [round(r, 4) for r in ratios],
        "stages": _stage_table(node),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=10_000)
    ap.add_argument("--txs", type=int, default=2, help="transfers per block")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    out = bench_quick(args.blocks, args.txs, args.repeats)
    from p1_tpu.hashx.perf_record import RECORDED_HOST_INGEST_BPS

    print(
        json.dumps(
            {
                "metric": "telemetry_overhead_pct",
                "value": out["overhead_pct"],
                "unit": "%",
                "n_blocks": args.blocks,
                "txs_per_block": args.txs,
                "ingest_with_telemetry_vs_recorded": round(
                    out["ingest_telemetry_bps"] / RECORDED_HOST_INGEST_BPS,
                    2,
                ),
                **out,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
