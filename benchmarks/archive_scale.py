"""Archive-scale benchmark: a synthetic multi-million-block SEGMENTED
store, and what it costs to build, resume, boot, and query (round 18 —
ROADMAP item 5's acceptance shape).

The store is coinbase-only and LINEAR (genesis at record 0, ordinal ==
height — the compacted/archive shape), crafted at the BYTE level: one
template coinbase transaction whose seq field is patched per height,
headers packed directly, records appended through
``SegmentedStore.append_raw`` — no Block objects anywhere in the build
loop, so generation runs at hashing speed and the 10M build is minutes,
not hours.  The first blocks are cross-checked byte-identical against
the real object serializer, so the synthetic store is exactly what a
node would have written.

Phases, each its own figure:

- **ingest** (``archive_ingest_bps``) — crafted records/s through the
  segmented append plane (CRC framing, rolls, hdrx seals; fsync off —
  the bulk-build shape);
- **resume** (``archive_resume_bps``) — whole-archive packed-header
  extraction (``SegmentedStore.packed_headers``): the scan-everything
  rate a header-plane rebuild or full PoW replay pays;
- **boot** (``archive_boot_s`` / ``archive_boot_rss_mb``) — a FRESH
  subprocess opens ``ArchiveChain`` (snapshot ledger + mmap'd header
  plane + bounded tail replay) and serves header/balance/proof
  queries; peak RSS is VmHWM from /proc, the fork-proof number.  The
  acceptance bar: 10M blocks under 1 GB.
- **query** (``archive_query_qps``) — random-height header queries
  against the booted archive (mmap page touches, no object builds).

Default is a 100k-block store (tier-1-adjacent wall time).  The full
ladder the PERF table records (100k / 1M / 10M) runs via ``--blocks``;
bench.py runs the 10M shape only under ``P1_BENCH_ARCHIVE=1``.
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_U32 = struct.Struct(">I")
_HDR = struct.Struct(">I32s32sIII")
_MINER = "bench-miner"

#: Snapshot cadence for the synthetic archive: the boot's tail replay
#: is bounded by one interval, so keep it small relative to the store.
SNAP_INTERVAL = 4096


def _tx_template(miner: str) -> tuple[bytearray, int]:
    """(mutable coinbase tx bytes, offset of the u64 seq field)."""
    from p1_tpu.core.tx import Transaction

    a = Transaction.coinbase(miner, 0).serialize()
    b = Transaction.coinbase(miner, 1).serialize()
    assert len(a) == len(b)
    # seq is the only differing field; it is a big-endian u64 ending at
    # the last differing byte.
    last_diff = max(i for i, (x, y) in enumerate(zip(a, b)) if x != y)
    seq_off = last_diff - 7
    return bytearray(a), seq_off


def build_archive(
    store_path: Path,
    n_blocks: int,
    segment_bytes: int,
    difficulty: int = 1,
    snap_interval: int = SNAP_INTERVAL,
) -> dict:
    """Craft the linear store + its snapshot sidecar; returns timings."""
    from hashlib import sha256

    from p1_tpu.chain import snapshot as snapmod
    from p1_tpu.chain.segstore import SegmentedStore
    from p1_tpu.core.block import Block
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.tx import BLOCK_REWARD, Transaction
    from p1_tpu.core.header import BlockHeader

    def sha256d(b: bytes) -> bytes:
        return sha256(sha256(b).digest()).digest()

    genesis = make_genesis(difficulty)
    tx, seq_off = _tx_template(_MINER)
    pack_seq = struct.Struct(">Q").pack_into
    base = (max(n_blocks - 1, 1) // snap_interval) * snap_interval
    anchor_payload: bytes | None = None
    store = SegmentedStore(
        store_path, fsync=False, segment_bytes=segment_bytes
    )
    t0 = time.perf_counter()
    store.append_raw(genesis.serialize(), height=0)
    prev = genesis.block_hash()
    ts0 = genesis.header.timestamp
    tx_len_prefix = _U32.pack(1) + _U32.pack(len(tx))
    for h in range(1, n_blocks):
        pack_seq(tx, seq_off, h)
        txid = sha256d(tx)
        hdr = _HDR.pack(1, prev, txid, ts0 + h, difficulty, 0)
        payload = hdr + tx_len_prefix + bytes(tx)
        if h <= 3:
            # Self-check: crafted bytes are EXACTLY what the object
            # layer serializes — the synthetic store is real.
            real = Block(
                header=BlockHeader(1, prev, txid, ts0 + h, difficulty, 0),
                txs=[Transaction.coinbase(_MINER, h)],
            ).serialize()
            assert payload == real, "crafted record diverged from objects"
        store.append_raw(payload, height=h)
        if h == base:
            anchor_payload = payload
        prev = sha256d(hdr)
        if h % 262144 == 0:
            # The span dict is the only O(chain) term in the builder;
            # the archive boot reindexes lazily from disk anyway.
            store._body_spans.clear()
    store.sync()
    build_s = time.perf_counter() - t0
    segments = len(store.segments)
    store.close()
    # The snapshot sidecar: the miner's whole subsidy stream at the
    # base height — byte-for-byte what chain.snapshot_state() packages
    # for this chain shape.
    assert anchor_payload is not None
    anchor = Block.deserialize(anchor_payload)
    balances = {_MINER: base * BLOCK_REWARD}
    manifest, chunks = snapmod.build_records(base, anchor, balances, {})
    snap_path = store_path.with_name(store_path.name + ".archsnap")
    snapmod.write_snapshot(snap_path, manifest, chunks)
    return {
        "build_s": round(build_s, 3),
        "archive_ingest_bps": round((n_blocks - 1) / build_s),
        "segments": segments,
        "snapshot_base": base,
        "store_bytes": sum(
            f.stat().st_size
            for f in store_path.with_name(store_path.name + ".d").iterdir()
        ),
    }


def measure_resume(store_path: Path) -> dict:
    """Whole-archive packed-header extraction rate (the full-scan
    resume/rebuild shape)."""
    from p1_tpu.chain.segstore import SegmentedStore

    store = SegmentedStore(store_path)
    t0 = time.perf_counter()
    raw, count = store.packed_headers()
    dt = time.perf_counter() - t0
    store.close()
    return {
        "archive_resume_bps": round(count / dt),
        "resume_records": count,
        "resume_s": round(dt, 3),
    }


def boot_phase(store_path: str, difficulty: int, queries: int) -> None:
    """Subprocess body: boot the archive, serve queries, report VmHWM."""
    import random

    from p1_tpu.chain.headerplane import ArchiveChain

    snap = store_path + ".archsnap"
    t0 = time.perf_counter()
    arch = ArchiveChain(store_path, snap, difficulty)
    boot_s = time.perf_counter() - t0
    rng = random.Random(18)
    height = arch.height
    # Header queries: random heights across the WHOLE archive.
    t0 = time.perf_counter()
    for _ in range(queries):
        h = rng.randrange(0, height + 1)
        assert arch.header_bytes_at(h) is not None
    query_s = time.perf_counter() - t0
    # Balance + cold proofs (plane txid lookups + one record read).
    assert arch.balance(_MINER) > 0
    tx, seq_off = _tx_template(_MINER)
    from hashlib import sha256

    proofs = 0
    t0 = time.perf_counter()
    for _ in range(min(100, queries)):
        h = rng.randrange(1, height + 1)
        struct.Struct(">Q").pack_into(tx, seq_off, h)
        txid = sha256(sha256(bytes(tx)).digest()).digest()
        proof = arch.tx_proof(txid)
        assert proof is not None and proof.height == h
        proofs += 1
    proof_s = time.perf_counter() - t0
    vmhwm_kb = 0
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmHWM:"):
                vmhwm_kb = int(line.split()[1])
    arch.close()
    print(
        json.dumps(
            {
                "archive_boot_s": round(boot_s, 4),
                "archive_boot_rss_mb": round(vmhwm_kb / 1024.0, 1),
                "archive_query_qps": round(queries / query_s),
                "archive_proof_qps": round(proofs / proof_s),
                "height": height,
            }
        )
    )


def measure_boot(store_path: Path, difficulty: int, queries: int) -> dict:
    """Run the boot phase in a FRESH process so VmHWM is the archive
    serving footprint, not this builder's."""
    proc = subprocess.run(
        [
            sys.executable,
            __file__,
            "--phase",
            "boot",
            "--store",
            str(store_path),
            "--difficulty",
            str(difficulty),
            "--queries",
            str(queries),
        ],
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"boot phase failed: {proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_archive(
    n_blocks: int,
    segment_bytes: int = 16 << 20,
    difficulty: int = 1,
    queries: int = 2000,
    keep: str | None = None,
) -> dict:
    import tempfile

    ctx = (
        tempfile.TemporaryDirectory(prefix="p1archive")
        if keep is None
        else None
    )
    tmp = Path(ctx.name) if ctx is not None else Path(keep)
    tmp.mkdir(parents=True, exist_ok=True)
    try:
        store_path = tmp / "archive.dat"
        out = {"blocks": n_blocks, "segment_bytes": segment_bytes}
        if not store_path.exists():
            out.update(build_archive(store_path, n_blocks, segment_bytes))
        out.update(measure_resume(store_path))
        out.update(measure_boot(store_path, difficulty, queries))
        return out
    finally:
        if ctx is not None:
            ctx.cleanup()


def bench_quick(blocks: int = 100_000) -> dict:
    """The bench.py probe: the 100k shape (seconds of wall time), same
    code path as the 10M acceptance run."""
    return bench_archive(blocks)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", type=int, default=100_000)
    ap.add_argument("--segment-mb", type=int, default=16)
    ap.add_argument("--difficulty", type=int, default=1)
    ap.add_argument("--queries", type=int, default=2000)
    ap.add_argument(
        "--keep", default=None, help="build/reuse the store in this dir"
    )
    ap.add_argument(
        "--phase", choices=("all", "boot"), default="all"
    )
    ap.add_argument("--store", default=None, help="(boot phase) store path")
    args = ap.parse_args()
    if args.phase == "boot":
        boot_phase(args.store, args.difficulty, args.queries)
        return
    out = bench_archive(
        args.blocks,
        segment_bytes=args.segment_mb << 20,
        difficulty=args.difficulty,
        queries=args.queries,
        keep=args.keep,
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
