"""Maintenance-cadence benchmark: the always-on node's steady costs.

Round 20's two acceptance figures, measured in ONE run on one ledger
shape (the bench.py same-session convention):

- **continuous snapshots** — rebuilds/sec for the incremental
  per-checkpoint snapshot build (``build_records_incremental``,
  O(delta): only the accounts the blocks since the last checkpoint
  touched re-encode and re-hash) against the full rebuild
  (``build_records``, O(accounts)) it replaces.  The ratio is the
  cadence headroom: how much tighter a node can publish snapshot
  heights without the build dominating its block budget.
- **live rebase latency** — milliseconds for ``Chain.rebase`` to
  advance an in-RAM base past a deep history (the in-RAM half of
  `p1 maintain rebase`; the store half is sequential segment IO and
  measured by the archive bench).  This is the stall an operator's
  rebase command costs a serving node's event loop, so it has to stay
  in single-digit milliseconds at the default keep depths.

Shapes: ``--accounts`` ledger entries (default 100k; the 1M acceptance
shape is ``--accounts 1000000``), ``--delta`` dirty accounts per
incremental build (default 64 — a generous per-checkpoint touch set at
the 4-block test cadence), ``--blocks`` in-RAM chain length for the
rebase probe.

One JSON line; ``bench_quick`` is the bench.py probe (small shapes,
same code paths) guarded by ``RECORDED_SNAPSHOT_CADENCE_BPS`` /
``RECORDED_REBASE_MS`` in hashx/perf_record.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _ledger(accounts: int) -> tuple[dict, dict]:
    balances = {f"acct-{i:07d}": 50 + (i % 97) for i in range(accounts)}
    nonces = {k: i % 5 for i, k in enumerate(balances)}
    return balances, nonces


def bench_snapshot_cadence(
    accounts: int = 100_000, delta: int = 64, repeats: int = 3
) -> dict:
    """Incremental vs full snapshot build over one ``accounts``-sized
    ledger, ``delta`` dirty accounts per incremental round.  Both paths
    build the SAME post-mutation state (the identity is test-pinned in
    tests/test_maintenance.py; here we only time it)."""
    from p1_tpu.chain.snapshot import build_records, build_records_incremental
    from p1_tpu.node.testing import make_blocks

    block = make_blocks(1, difficulty=1)[-1]
    balances, nonces = _ledger(accounts)
    # Warm state: the residue every steady-state checkpoint build has.
    _, _, state, _ = build_records_incremental(
        None, 4, block, balances, nonces, set(balances)
    )
    keys = sorted(balances)
    full_s = []
    incr_s = []
    reused = 0
    for r in range(repeats):
        dirty = {keys[(r * delta + j) % accounts] for j in range(delta)}
        for k in dirty:
            balances[k] += 1  # in-place: no key shift, the honest delta
        t0 = time.perf_counter()
        build_records(4, block, balances, nonces)
        full_s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _, chunks, state, got = build_records_incremental(
            state, 4, block, balances, nonces, dirty
        )
        incr_s.append(time.perf_counter() - t0)
        reused = got
    full = min(full_s)
    incr = min(incr_s)
    return {
        "accounts": accounts,
        "delta_accounts": delta,
        "snapshot_full_builds_per_sec": round(1.0 / full, 1),
        "snapshot_incr_builds_per_sec": round(1.0 / incr, 1),
        "snapshot_cadence_speedup": round(full / incr, 1),
        "snapshot_chunks_reused": reused,
        "snapshot_chunks_total": len(chunks),
    }


def bench_rebase(blocks: int = 192, interval: int = 16) -> dict:
    """In-RAM rebase latency: a ``blocks``-deep chain advances its base
    to the newest checkpoint ``interval`` blocks behind the tip — the
    on-loop cost of `p1 maintain rebase` (the durable store half runs
    off-loop and is the archive bench's territory)."""
    from p1_tpu.chain.chain import Chain
    from p1_tpu.node.testing import make_blocks

    mined = make_blocks(blocks, difficulty=1)
    chain = Chain(1)
    chain.checkpoint_interval = interval
    for b in mined[1:]:
        res = chain.add_block(b, trusted=True)
        assert res.status.value == "accepted", res
    target = ((chain.height - interval) // interval) * interval
    t0 = time.perf_counter()
    stats = chain.rebase(target)
    rebase_ms = (time.perf_counter() - t0) * 1e3
    return {
        "rebase_blocks": blocks,
        "rebase_ms": round(rebase_ms, 3),
        "rebase_dropped_blocks": stats["dropped_blocks"],
        "rebase_freed_bytes": stats["freed_bytes"],
    }


def bench_quick(
    accounts: int = 20_000, delta: int = 64, blocks: int = 96
) -> dict:
    """The bench.py probe: small shapes, the same code paths as the
    acceptance run (tracks the pinned 100k figure within the guard
    band at a fraction of the cost)."""
    out = bench_snapshot_cadence(accounts=accounts, delta=delta, repeats=3)
    out.update(bench_rebase(blocks=blocks, interval=16))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--accounts", type=int, default=100_000)
    ap.add_argument("--delta", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--blocks", type=int, default=192)
    args = ap.parse_args(argv)
    out = bench_snapshot_cadence(
        accounts=args.accounts, delta=args.delta, repeats=args.repeats
    )
    out.update(bench_rebase(blocks=args.blocks))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
