"""Snapshot-boot benchmark: seconds from cold store to serving queries.

ROADMAP item 2's acceptance shape: a fresh node joining at 100k blocks
must serve balance/header/proof queries in seconds from a state
snapshot, against the batched full revalidation it replaces — both
measured in the SAME run, on the same store, so the speedup is never a
cross-session artifact (the bench.py convention).

Three timed paths over one mined store:

- **revalidate** — ``ChainStore.load_chain(trusted=False)``: the full
  untrusted boot (PoW, merkle, batched Ed25519 where transfers exist,
  connect-time ledger) — what a snapshotless new node pays.
- **trusted** — ``load_chain(trusted=True)``: the fast restart of a
  node's OWN store, for context (a snapshot boot competes with the
  untrusted figure, not this one — a fresh node has no own store).
- **snapshot** — ``load_snapshot`` (CRC framing + chunk digests + state
  root) → ``Chain.from_snapshot`` → first balance + header + tip-proof
  query answered.  O(accounts), independent of chain length: the whole
  point.

The default shape mines coinbase-only blocks with a rotating miner
identity (``--accounts`` distinct ids) plus signed transfers every
``--tx-every`` blocks, so the revalidation baseline pays real signature
checks without the fixture build drowning in pure-Python signing.

One JSON line; ``bench_quick`` is the bench.py probe (small store,
same code path) guarded by ``RECORDED_SNAPSHOT_BOOT_S``.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_store(
    path,
    n_blocks: int,
    accounts: int = 1000,
    tx_every: int = 50,
    difficulty: int = 1,
):
    """Mine an ``n_blocks`` chain to ``path``: coinbase rotates over
    ``accounts`` miner ids (so the ledger holds that many balances) and
    every ``tx_every``-th block carries two signed transfers (so the
    revalidation baseline pays real Ed25519 work)."""
    from p1_tpu.chain import ChainStore
    from p1_tpu.core.block import Block, merkle_root
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    alice = Keypair.from_seed_text("snapshot-boot-alice")
    bob = Keypair.from_seed_text("snapshot-boot-bob")
    miner = Miner(backend=get_backend("cpu"))
    genesis = make_genesis(difficulty)
    chain_tag = genesis.block_hash()
    store = ChainStore(path, fsync=False)
    store.acquire()
    store.append(genesis)
    prev = genesis
    alice_funds = 0
    alice_seq = 0
    for height in range(1, n_blocks + 1):
        # Alice's coinbase heights fund her transfers later.
        mine_to_alice = height % tx_every == 1
        miner_id = (
            alice.account if mine_to_alice else f"acct-{height % accounts:06d}"
        )
        txs = [Transaction.coinbase(miner_id, height)]
        if mine_to_alice:
            alice_funds += txs[0].amount
        if tx_every and height % tx_every == 0 and alice_funds >= 4:
            for _ in range(2):
                txs.append(
                    Transaction.transfer(
                        alice, bob.account, 1, 1, alice_seq, chain=chain_tag
                    )
                )
                alice_seq += 1
                alice_funds -= 2
        header = BlockHeader(
            version=1,
            prev_hash=prev.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=prev.header.timestamp + 1,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(header)
        assert sealed is not None
        prev = Block(sealed, tuple(txs))
        store.append(prev)
    store.sync()
    store.close()


def bench_store(path, difficulty: int = 1, interval: int = 0) -> dict:
    """All three boot measurements over an existing store; also writes
    (and fully verifies) the snapshot file next to it."""
    from p1_tpu.chain import ChainStore
    from p1_tpu.chain import snapshot as chain_snapshot
    from p1_tpu.chain.chain import Chain

    out: dict = {}

    # Untrusted full revalidation (the figure a snapshot boot replaces).
    store = ChainStore(path)
    t0 = time.perf_counter()
    chain = store.load_chain(difficulty, trusted=False)
    out["revalidate_boot_s"] = round(time.perf_counter() - t0, 3)
    out["height"] = chain.height
    store.close()

    # Trusted resume, for context.
    store = ChainStore(path)
    t0 = time.perf_counter()
    store.load_chain(difficulty, trusted=True)
    out["trusted_boot_s"] = round(time.perf_counter() - t0, 3)
    store.close()

    # Snapshot create (NOT part of the boot figure: the SERVING side
    # pays it once per checkpoint) ...
    if interval > 0:
        chain.checkpoint_interval = interval
        chain.state_checkpoints.clear()
    state = chain.snapshot_state()
    assert state is not None, "store too short for a checkpoint"
    height, block, balances, nonces, _root = state
    manifest_payload, chunks = chain_snapshot.build_records(
        height, block, balances, nonces
    )
    snap_file = Path(str(path) + ".bench-snapshot")
    chain_snapshot.write_snapshot(snap_file, manifest_payload, chunks)
    out["snapshot_height"] = height
    out["snapshot_accounts"] = len(set(balances) | set(nonces))
    out["snapshot_bytes"] = snap_file.stat().st_size

    # ... and the snapshot BOOT: verify + build the assumed chain +
    # answer one balance, one header, and one tip-proof query.
    t0 = time.perf_counter()
    snap = chain_snapshot.load_snapshot(snap_file)
    assumed = Chain.from_snapshot(difficulty, snap)
    anchor = assumed.tip
    assert assumed.balance(anchor.txs[0].recipient) >= 0
    assert assumed.header_of(assumed.tip_hash) is not None
    proof = assumed.tx_proof(anchor.txs[0].txid())
    assert proof is not None
    out["snapshot_boot_s"] = round(time.perf_counter() - t0, 3)
    out["boot_speedup"] = round(
        out["revalidate_boot_s"] / max(out["snapshot_boot_s"], 1e-9), 1
    )
    snap_file.unlink()
    return out


def bench_quick(blocks: int = 2000, repeats: int = 3) -> dict:
    """The bench.py probe: a small same-shape store, best-of-N on the
    snapshot boot (the revalidation baseline runs once — it dominates
    the probe's budget as it is)."""
    with tempfile.TemporaryDirectory(prefix="p1snapboot") as tmp:
        path = Path(tmp) / "store.dat"
        build_store(path, blocks)
        best: dict = {}
        for _ in range(repeats):
            out = bench_store(path)
            if not best or out["snapshot_boot_s"] < best["snapshot_boot_s"]:
                best = out
        return best


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", type=int, default=100_000)
    ap.add_argument("--accounts", type=int, default=1000)
    ap.add_argument("--tx-every", type=int, default=50)
    ap.add_argument("--difficulty", type=int, default=1)
    ap.add_argument(
        "--store", default=None, help="reuse this store instead of mining"
    )
    ap.add_argument(
        "--interval", type=int, default=0, help="checkpoint interval override"
    )
    args = ap.parse_args()
    if args.store:
        out = bench_store(args.store, args.difficulty, args.interval)
        out["blocks"] = out["height"]
    else:
        with tempfile.TemporaryDirectory(prefix="p1snapboot") as tmp:
            path = Path(tmp) / "store.dat"
            t0 = time.perf_counter()
            build_store(
                path,
                args.blocks,
                accounts=args.accounts,
                tx_every=args.tx_every,
                difficulty=args.difficulty,
            )
            build_s = time.perf_counter() - t0
            out = bench_store(path, args.difficulty, args.interval)
            out["build_s"] = round(build_s, 3)
    print(json.dumps({"config": "snapshot_boot", **out}))


if __name__ == "__main__":
    main()
