"""Memory-bounded operation benchmark: peak RSS and refetch latency.

VERDICT r7 weak #3: the whole-chain in-RAM index is linear and unbounded
— 346 MB peak RSS at 100k blocks (docs/PERF.md "Restart at scale"), so a
node serving a 1M-block chain would sit near 3.5 GB before a single peer
connects.  The governor's memory-bounded operation (node/governor.py
layer 2) keeps headers and metadata resident but evicts block *bodies*
once they are durably refetchable from the append-only store
(``Chain.evict_bodies`` / ``ChainStore.read_body``), and streams the
resume itself through the same eviction (``load_chain(body_cache=N)``)
so boot never materializes the O(chain) object graph either.

This harness measures exactly that claim, same contract as bench.py:
print ONE JSON line, measured on this machine, no estimates.  For each
chain length it reports, from a fresh subprocess each (``ru_maxrss`` is
a high-water mark — it never comes back down, so resident and bounded
resumes must not share a process):

- **resident** — ``load_chain(trusted=True)`` with ``body_cache=0``:
  the historical fully-resident behavior (the "before" column).
- **bounded** — ``load_chain(trusted=True, body_cache=N)``: peak RSS,
  resume wall time, bodies evicted, and the on-demand body refetch
  latency (p50/p95 over deep-history ``chain.get`` calls, which miss
  the keep window by construction).

The fixture mirrors the round-5 "Restart at scale" store: difficulty 1,
one signed transfer every other block (~0.5/block), built once and
snapshotted at each requested height.  Runs anywhere (no TPU, no jax
import on the measured path — the subprocess RSS is interpreter + chain,
which is what a node's memory plan has to budget for).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import tempfile
import time
from pathlib import Path

# Runnable as `python benchmarks/memory_bound.py` from a checkout.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DIFFICULTY = 1


def build_store(heights: list[int], outdir: Path) -> dict[int, Path]:
    """One incremental build, snapshotted at each requested height;
    returns {height: store path}.  The builder keeps the chain resident
    (validity needs the ledger anyway) and appends as it goes — the
    store file at height H is byte-identical to a node that mined/synced
    H blocks."""
    from p1_tpu.chain.chain import Chain
    from p1_tpu.chain.store import ChainStore
    from p1_tpu.core.block import Block, merkle_root
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    alice = Keypair.from_seed_text("memory-bound-alice")
    chain = Chain(DIFFICULTY)
    tag = chain.genesis.block_hash()
    miner = Miner(backend=get_backend("cpu"))
    top = max(heights)
    path = outdir / f"membench-{top}.chain"
    store = ChainStore(path, fsync=False)
    snapshots: dict[int, Path] = {}
    seq = 0
    try:
        for height in range(1, top + 1):
            txs = [Transaction.coinbase(alice.account, height)]
            if height > 1 and height % 2 == 0:
                txs.append(
                    Transaction.transfer(alice, "bob", 1, 1, seq, chain=tag)
                )
                seq += 1
            parent = chain.tip
            draft = BlockHeader(
                version=1,
                prev_hash=parent.block_hash(),
                merkle_root=merkle_root([tx.txid() for tx in txs]),
                # +1 s per block: strictly increasing (the consensus
                # floor) without overflowing uint32 at 100k heights the
                # way a cumulative +height cadence does.
                timestamp=parent.header.timestamp + 1,
                difficulty=DIFFICULTY,
                nonce=0,
            )
            sealed = miner.search_nonce(draft)
            assert sealed is not None
            block = Block(sealed, tuple(txs))
            res = chain.add_block(block)
            assert res.status.value == "accepted", res
            store.append(block)
            if height in heights:
                store.sync()
                snap = outdir / f"membench-{height}.chain"
                if snap != path:
                    snap.write_bytes(path.read_bytes())
                snapshots[height] = snap
    finally:
        store.close()
    return snapshots


def peak_rss_bytes() -> int:
    """This process's peak resident set.  ``VmHWM`` (reset by execve —
    it lives on the mm) rather than ``ru_maxrss`` (task accounting that
    SURVIVES fork+exec on Linux, so a subprocess forked from a fat
    driver would inherit the driver's high-water mark and every
    measurement would read as the parent's peak)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def measure(store_path: Path, body_cache: int) -> dict:
    """One resume measurement, in THIS process (the driver runs it via a
    fresh subprocess per data point)."""
    from p1_tpu.chain.store import ChainStore

    store = ChainStore(store_path, fsync=False)
    t0 = time.perf_counter()
    chain = store.load_chain(DIFFICULTY, trusted=True, body_cache=body_cache)
    resume_s = time.perf_counter() - t0
    out = {
        "body_cache": body_cache,
        "blocks": chain.height,
        "resume_s": round(resume_s, 3),
        "peak_rss_bytes": peak_rss_bytes(),
        "resident_body_bytes": chain.resident_body_bytes,
        "bodies_evicted": chain.bodies_evicted,
    }
    if body_cache > 0 and chain.height > body_cache:
        # Deep-history refetch latency: every sampled height is below
        # the keep window, so each get() is a real pread + deserialize.
        deep = chain.height - body_cache
        step = max(1, deep // 256)
        lats = []
        for h in range(1, deep, step):
            bh = chain._main_hashes[h]
            t0 = time.perf_counter()
            blk = chain.get(bh)
            lats.append(time.perf_counter() - t0)
            assert blk is not None and blk.block_hash() == bh
        lats.sort()
        out["refetch_samples"] = len(lats)
        out["refetch_us_p50"] = round(lats[len(lats) // 2] * 1e6, 1)
        out["refetch_us_p95"] = round(lats[int(len(lats) * 0.95)] * 1e6, 1)
    store.close()
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--blocks",
        default="10000,100000",
        help="comma-separated chain lengths to measure (default 10k,100k)",
    )
    ap.add_argument(
        "--body-cache",
        type=int,
        default=1024,
        help="keep-recent window for the bounded runs (default 1024)",
    )
    ap.add_argument(
        "--measure",
        help="(internal) run one resume measurement against this store "
        "and print its JSON — the driver spawns one subprocess per "
        "data point so ru_maxrss high-water marks stay independent",
    )
    args = ap.parse_args()
    if args.measure:
        print(json.dumps(measure(Path(args.measure), args.body_cache)))
        return

    heights = sorted({int(x) for x in args.blocks.split(",") if x})
    results = []
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        snapshots = build_store(heights, Path(tmp))
        build_s = time.perf_counter() - t0
        for height in heights:
            snap = snapshots[height]
            row = {"blocks": height, "store_bytes": snap.stat().st_size}
            for label, cache in (
                ("resident", 0),
                ("bounded", args.body_cache),
            ):
                proc = subprocess.run(
                    [
                        sys.executable,
                        __file__,
                        "--measure",
                        str(snap),
                        "--body-cache",
                        str(cache),
                    ],
                    capture_output=True,
                    text=True,
                    check=True,
                )
                row[label] = json.loads(proc.stdout)
            results.append(row)
    print(
        json.dumps(
            {
                "metric": "resume_peak_rss_bytes",
                "value": results[-1]["bounded"]["peak_rss_bytes"],
                "unit": "bytes",
                "vs_resident": round(
                    results[-1]["bounded"]["peak_rss_bytes"]
                    / results[-1]["resident"]["peak_rss_bytes"],
                    3,
                ),
                "body_cache": args.body_cache,
                "build_s": round(build_s, 1),
                "runs": results,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
