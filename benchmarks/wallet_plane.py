"""Wallet push-plane benchmark: live subscriptions at scale, host-side.

ROUND21's "done" bar: one process sustaining >= 100k live wallet
subscriptions on a SubscriptionManager with per-block notify latency
(p95) under one block interval — plus a small real-socket
submit -> confirm -> push end-to-end measurement (the SLO row in
docs/PERF.md).  The 100k figure is what makes the shared-decode design
honest: notify cost is O(filter decode + subs x items), NOT
O(subs x filter decode), so one decode is amortized across every
session (node/subscriptions.py).

Measurements:

- **wallet_subs** — live subscriptions held while the notify figures
  below were taken (the scale knob, default 100_000).
- **notify_p95_ms / notify_mean_ms** — per-block connect-to-delivered
  latency of SubscriptionManager.notify() across the measured blocks:
  decode the block's filter once, probe every session's watch set,
  personalize matched events, hand every non-matched session the one
  shared pre-encoded frame.
- **notify_events_per_sec** — delivered events/s during those blocks
  (subs x blocks / total notify time).
- **push_e2e_ms** — real sockets: a node mining on loopback, a
  `client.watch` session subscribed to the recipient account; wall
  time from send_tx() to the verified matched EVENT arriving (submit,
  mine/confirm, filter build, push, client-side commitment check).
- **fleet_*** (round 22) — the fleet-provisioning figures:
  ``fleet_cold_start_s`` is `p1 serve --bootstrap`'s
  decide-to-serving-ready wall time (snapshot-based, bounded by blocks
  above the base — not chain length), and the ``bench_fleet`` family
  is the kill-one-replica proof at wall-clock scale: N replicas x many
  ReplicaSet-spread sessions on one store, the most-loaded replica
  killed mid-push, per-event notify p95 split before/after the kill,
  failovers, peak ``subs.queue_depth_bytes`` on survivors, and
  ``fleet_missed`` (sessions whose stream went non-contiguous or
  unmatched — the acceptance bar is 0).

JSON: {"metric": "wallet_subs", "value": ..., "notify_p95_ms": ...}
— one line, measured, no estimates (the bench.py contract).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.query_plane import build_chain  # noqa: E402


class _ThrottledSource:
    """ChainSubSource with a movable tip: the fixture chain is fully
    built up front, then 'connected' one block at a time so each
    notify() measures exactly one block's push cost."""

    def __init__(self, chain):
        self._chain = chain
        self.tip = 0

    @property
    def tip_height(self) -> int:
        return self.tip

    def hash_at(self, height: int):
        if not 0 <= height <= self.tip:
            return None
        return self._chain.main_hash_at(height)

    def raw_header_at(self, height: int):
        bh = self.hash_at(height)
        return None if bh is None else self._chain.header_of(bh).serialize()

    def filter_at(self, height: int):
        bh = self.hash_at(height)
        return None if bh is None else self._chain.block_filter(bh)

    def fheader_at(self, height: int):
        if height > self.tip:
            return None
        return self._chain.filter_headers.header_at(height)

    def block_items_at(self, height: int):
        from p1_tpu.node.subscriptions import block_items_index

        bh = self.hash_at(height)
        return None if bh is None else block_items_index(self._chain.get(bh))


def bench_subs(
    subs: int = 100_000,
    warm_blocks: int = 4,
    measure_blocks: int = 12,
    txs: int = 24,
    matched_fraction: float = 0.01,
) -> dict:
    """>= ``subs`` live sessions on one SubscriptionManager; p95 notify
    latency per connected block.  ``matched_fraction`` of the sessions
    watch an account the fixture blocks actually pay (every block's
    transfers go to "bob"), the rest watch cold accounts — the
    realistic shape: almost every wallet is a non-match almost always.
    Delivery sinks count bytes and never backpressure (buffer 0), so
    the figure isolates the push plane, not the benchmark's sockets."""
    from p1_tpu.node.subscriptions import SubscriptionManager

    chain = build_chain(warm_blocks + measure_blocks, txs, difficulty=1)
    source = _ThrottledSource(chain)
    mgr = SubscriptionManager(source)

    delivered = [0]

    async def _sink(payload: bytes) -> None:
        delivered[0] += 1

    def _buf() -> int:
        return 0

    def _close() -> None:
        pass

    async def _run() -> dict:
        n_matched = int(subs * matched_fraction)
        for i in range(subs):
            items = (
                [b"bob"]
                if i < n_matched
                else [b"cold-account-%d" % i, b"cold-change-%d" % i]
            )
            ok = await mgr.subscribe(
                i, items, None, send=_sink, buffer_size=_buf, close=_close
            )
            assert ok
        assert len(mgr) == subs

        # Warm-up: first connects touch cold caches (filter decode path).
        for h in range(1, warm_blocks + 1):
            source.tip = h
            await mgr.notify()

        samples_ms = []
        t_total = 0.0
        for h in range(warm_blocks + 1, warm_blocks + measure_blocks + 1):
            source.tip = h
            t0 = time.perf_counter()
            await mgr.notify()
            dt = time.perf_counter() - t0
            samples_ms.append(dt * 1000.0)
            t_total += dt
        samples_ms.sort()
        p95 = samples_ms[min(len(samples_ms) - 1, int(0.95 * len(samples_ms)))]
        return {
            "wallet_subs": len(mgr),
            "notify_p95_ms": round(p95, 2),
            "notify_mean_ms": round(
                sum(samples_ms) / len(samples_ms), 2
            ),
            "notify_events_per_sec": round(subs * measure_blocks / t_total),
            "events_delivered": delivered[0],
            "measure_blocks": measure_blocks,
        }

    return asyncio.run(_run())


def bench_push_e2e(difficulty: int = 20, timeout: float = 60.0) -> dict:
    """submit -> confirm -> push over real loopback sockets: a mining
    node, one watch session on the recipient account, wall time from
    send_tx to the verified matched EVENT.

    The default difficulty pins block cadence near one per second; at
    test-grade difficulties this host mines hundreds of blocks a
    second, which measures the watch client's replay treadmill instead
    of the push path."""
    from p1_tpu.config import NodeConfig
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.client import send_tx, watch
    from p1_tpu.node.node import Node

    alice = Keypair.from_seed_text("wallet-plane-alice")

    async def _run() -> dict:
        node = Node(
            NodeConfig(
                host="127.0.0.1",
                port=0,
                difficulty=difficulty,
                mine=True,
                miner_id=alice.account,
            )
        )
        await node.start()
        try:
            # Let the miner fund alice before the spend.
            for _ in range(600):
                if node.chain.balance(alice.account) >= 2:
                    break
                await asyncio.sleep(0.05)
            gen = watch(
                "127.0.0.1",
                node.port,
                ["bob-wallet-plane"],
                difficulty,
                max_session_failures=3,
            )
            t0 = None
            latency_ms = None
            try:
                agen = gen.__aiter__()
                # First event proves the session is live before we time.
                await asyncio.wait_for(agen.__anext__(), timeout)
                tx = Transaction.transfer(
                    alice,
                    "bob-wallet-plane",
                    1,
                    1,
                    0,
                    chain=node.chain.genesis.block_hash(),
                )
                t0 = time.perf_counter()
                await send_tx("127.0.0.1", node.port, tx, difficulty)
                while True:
                    ev = await asyncio.wait_for(agen.__anext__(), timeout)
                    if ev["matched"]:
                        latency_ms = (time.perf_counter() - t0) * 1000.0
                        break
            finally:
                await gen.aclose()
            return {"push_e2e_ms": round(latency_ms, 1)}
        finally:
            await node.stop()

    return asyncio.run(_run())


def bench_quick(subs: int = 20_000, measure_blocks: int = 8) -> dict:
    """The bench.py hook: the same notify measurement at a size that
    keeps the headline bench fast; the 100k figure is main()'s job."""
    return bench_subs(subs=subs, warm_blocks=2, measure_blocks=measure_blocks)


def bench_cold_start(
    chain_blocks: int = 60,
    difficulty: int = 12,
    snapshot_interval: int = 16,
) -> dict:
    """Replica cold-start figure (round 22): wall seconds from `p1
    serve --bootstrap <node>` deciding to join until its store is
    serving-ready — PoW-verified header skeleton, chunk-verified
    snapshot pinned to it, adopted filter headers, bodies above the
    base (node/provision.py bootstrap_store).  The point of the figure:
    it is bounded by blocks ABOVE the snapshot base, not by chain
    length — an IBD is bounded by chain length."""
    import tempfile

    from p1_tpu.chain.store import ChainStore
    from p1_tpu.config import NodeConfig
    from p1_tpu.node.node import Node
    from p1_tpu.node.provision import bootstrap_store
    from p1_tpu.node.testing import make_blocks

    blocks = make_blocks(chain_blocks, difficulty, miner_id="fleet-src")

    async def _run() -> dict:
        with tempfile.TemporaryDirectory() as d:
            src = str(Path(d) / "node.dat")
            st = ChainStore(src, fsync=False)
            try:
                for b in blocks[1:]:
                    st.append(b)
                st.sync()
            finally:
                st.close()
            node = Node(
                NodeConfig(
                    host="127.0.0.1",
                    port=0,
                    difficulty=difficulty,
                    mine=False,
                    store_path=src,
                    snapshot_interval=snapshot_interval,
                )
            )
            await node.start()
            try:
                report = await bootstrap_store(
                    str(Path(d) / "replica.dat"),
                    [("127.0.0.1", node.port)],
                    difficulty,
                )
            finally:
                await node.stop()
            return {
                "fleet_cold_start_s": report["cold_start_s"],
                "fleet_cold_start_base": report["base"],
                "fleet_cold_start_tip": report["tip"],
                "fleet_cold_start_blocks_fetched": report["blocks_fetched"],
            }

    return asyncio.run(_run())


def bench_fleet(
    replicas: int = 3,
    sessions: int = 48,
    blocks: int = 12,
    kill_at: int = 6,
    difficulty: int = 12,
    interval_s: float = 0.2,
) -> dict:
    """The kill-one-replica figure (round 22): ``replicas`` replica
    workers on ONE chain store, ``sessions`` wallet watch sessions
    spread across them by ReplicaSet policy (distinct spread keys), a
    writer appending one block per ``interval_s`` — and the most-loaded
    replica killed mid-push at height ``kill_at``.  Measured: per-event
    notify latency (append-to-verified-arrival) p95 overall and split
    before/after the kill (the "p95 stays flat" claim), total
    failovers, peak ``subs.queue_depth_bytes`` on the survivors, and
    missed confirmations (every block pays the watched account, so a
    session's stream must stay contiguous and fully matched — missed ==
    0 is the acceptance bar, not a statistic)."""
    import tempfile

    from p1_tpu.chain.store import ChainStore
    from p1_tpu.node.client import ReplicaSet, watch
    from p1_tpu.node.queryplane import serve_replica
    from p1_tpu.node.testing import make_blocks

    WARM = 2
    chain_blocks = make_blocks(blocks, difficulty, miner_id="fleet-acct")

    async def _run() -> dict:
        with tempfile.TemporaryDirectory() as d:
            store_path = str(Path(d) / "fleet.dat")
            store = ChainStore(store_path, fsync=False)
            for h in range(1, WARM + 1):
                store.append(chain_blocks[h], h)
            store.sync()

            srvs = [
                await serve_replica(
                    store_path, difficulty, refresh_interval_s=0.02
                )
                for _ in range(replicas)
            ]
            targets = [("127.0.0.1", s.port) for s in srvs]
            sets = [
                ReplicaSet(targets, spread_key=k) for k in range(sessions)
            ]
            arrivals: list[dict[int, float]] = [{} for _ in range(sessions)]
            streams: list[list] = [[] for _ in range(sessions)]

            async def _session(k: int) -> None:
                try:
                    async for ev in watch(
                        "127.0.0.1", srvs[0].port, ["fleet-acct"],
                        difficulty, replica_set=sets[k],
                        cross_check_every=0, reconnect_delay_s=0.05,
                        max_session_failures=None,
                    ):
                        arrivals[k][ev["height"]] = time.perf_counter()
                        streams[k].append(ev)
                except asyncio.CancelledError:
                    raise

            tasks = [
                asyncio.create_task(_session(k)) for k in range(sessions)
            ]
            # All ears before the measured appends.
            for _ in range(600):
                if sum(len(s.subscriptions) for s in srvs) >= sessions:
                    break
                await asyncio.sleep(0.02)

            appended_at: dict[int, float] = {}
            killed = None
            queue_peak = 0
            for h in range(WARM + 1, blocks + 1):
                store.append(chain_blocks[h], h)
                store.sync()
                appended_at[h] = time.perf_counter()
                if h == kill_at:
                    # The directed kill: the replica carrying the most
                    # active sessions, mid-push.
                    tally = {}
                    for s in sets:
                        if s.active is not None:
                            tally[s.active] = tally.get(s.active, 0) + 1
                    victim = max(sorted(tally), key=lambda t: tally[t])
                    killed = targets.index(victim)
                    await srvs[killed].stop()
                await asyncio.sleep(interval_s)
                queue_peak = max(
                    queue_peak,
                    *(
                        s.subscriptions.queue_depth_bytes
                        for i, s in enumerate(srvs)
                        if i != killed
                    ),
                )
            # Every session must reach the final height (failover done).
            for _ in range(600):
                if all(blocks in a for a in arrivals):
                    break
                await asyncio.sleep(0.05)
            for task in tasks:
                task.cancel()
            for task in tasks:
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            for i, s in enumerate(srvs):
                if i != killed:
                    await s.stop()
            store.close()

            pre, post = [], []
            for a in arrivals:
                for h, t in a.items():
                    if h in appended_at:
                        (pre if h <= kill_at else post).append(
                            (t - appended_at[h]) * 1000.0
                        )
            def _p95(xs):
                if not xs:
                    return None
                xs = sorted(xs)
                return round(xs[min(len(xs) - 1, int(0.95 * len(xs)))], 2)
            missed = 0
            for s in streams:
                hs = [ev["height"] for ev in s]
                if hs != list(range(hs[0], hs[0] + len(hs))) or not all(
                    ev["matched"] for ev in s
                ):
                    missed += 1
            return {
                "fleet_replicas": replicas,
                "fleet_sessions": sessions,
                "fleet_killed_replica": killed,
                "fleet_failovers": sum(s.failovers for s in sets),
                "fleet_missed": missed,
                "fleet_notify_p95_ms": _p95(pre + post),
                "fleet_notify_p95_pre_kill_ms": _p95(pre),
                "fleet_notify_p95_post_kill_ms": _p95(post),
                "fleet_queue_depth_bytes_peak": queue_peak,
            }

    return asyncio.run(_run())


def bench_fleet_quick(replicas: int = 3, sessions: int = 24) -> dict:
    """The bench.py hook: a small kill-one-replica run plus the
    cold-start figure — fast enough for the headline bench, shaped
    exactly like the acceptance run."""
    out = bench_fleet(
        replicas=replicas, sessions=sessions, blocks=10, kill_at=5
    )
    out.update(bench_cold_start(chain_blocks=48))
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--subs", type=int, default=100_000)
    ap.add_argument("--blocks", type=int, default=12, help="measured blocks")
    ap.add_argument("--txs", type=int, default=24, help="transfers per block")
    ap.add_argument(
        "--skip-e2e",
        action="store_true",
        help="skip the real-socket submit->confirm->push measurement",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="run the kill-one-replica fleet figure instead of the "
        "single-node push plane",
    )
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument(
        "--sessions",
        type=int,
        default=48,
        help="wallet sessions spread across the fleet (--fleet)",
    )
    args = ap.parse_args()

    if args.fleet:
        res = bench_fleet(replicas=args.replicas, sessions=args.sessions)
        res.update(bench_cold_start())
        import os

        print(
            json.dumps(
                {
                    "metric": "fleet_notify_p95_ms",
                    "value": res["fleet_notify_p95_ms"],
                    "unit": "ms",
                    "cpu_count": os.cpu_count(),
                    **res,
                }
            )
        )
        return

    res = bench_subs(
        subs=args.subs, measure_blocks=args.blocks, txs=args.txs
    )
    if not args.skip_e2e:
        res.update(bench_push_e2e())

    import os

    try:
        load_1m, load_5m, _ = os.getloadavg()
    except OSError:
        load_1m = load_5m = None

    print(
        json.dumps(
            {
                "metric": "wallet_subs",
                "value": res["wallet_subs"],
                "unit": "live subscriptions",
                "load_avg_1m": load_1m,
                "load_avg_5m": load_5m,
                "cpu_count": os.cpu_count(),
                **res,
            }
        )
    )


if __name__ == "__main__":
    main()
