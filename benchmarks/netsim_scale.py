"""Simulator scale benchmark: nodes x virtual-seconds per wall-second.

The question this answers: how much mesh can one host simulate, and how
fast?  The metric is ``sim_nodes_per_sec`` = nodes x virtual_seconds /
wall_seconds — node-seconds of simulated network per second of real
time — measured on the partition-heal scenario (the corpus flagship:
mesh formation, gossip, a 60/40 cut, divergent mining, mass reorg on
heal).  The scale table (``--table``) feeds docs/PERF.md; the single
default run feeds ``bench.py``'s ``sim_nodes_per_sec`` line against the
pinned ``RECORDED_SIM_RATE`` (p1_tpu/hashx/perf_record.py).

Real sockets on this 1-vCPU host topped out around 7 nodes at 1x real
time, i.e. ~7 node-seconds/second; the simulator's figure is the
multiple of that wall this round removed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_sim(nodes: int = 200, seed: int = 0) -> dict:
    """One partition-heal run; returns the rate figures + the report."""
    from p1_tpu.node.scenarios import partition_heal

    report = partition_heal(nodes=nodes, seed=seed)
    rate = nodes * report["virtual_s"] / max(report["wall_s"], 1e-9)
    return {
        "nodes": nodes,
        "ok": report["ok"],
        "virtual_s": report["virtual_s"],
        "wall_s": report["wall_s"],
        "events": report["events"],
        "events_per_wall_s": round(report["events"] / max(report["wall_s"], 1e-9)),
        "sim_nodes_per_sec": round(rate, 1),
    }


def bench_far_field(
    nodes: int = 10_000, shards: int = 1, seed: int = 0
) -> dict:
    """One far-field scenario run (full-node core + header-only far
    field, node/farfield.py) at ``shards`` — the round-17 per-shard
    scaling row.  Rate metric: node-seconds of simulated mesh per wall
    second over the whole composed run, same definition as
    ``bench_sim`` so the two tables read against each other.  Honesty:
    far-field node-seconds are HEADER-ONLY node-seconds (no mempool,
    ledger, stores, supervision — docs/PERF.md spells out the model),
    and on a 1-vCPU host process shards ADD overhead; the sharding is
    for multi-core hosts."""
    from p1_tpu.node.scenarios import far_field

    report = far_field(nodes=nodes, seed=seed, shards=shards)
    rate = nodes * report["virtual_s"] / max(report["wall_s"], 1e-9)
    return {
        "nodes": nodes,
        "shards": shards,
        "shard_processes": report["shard_processes"],
        "ok": report["ok"],
        "virtual_s": report["virtual_s"],
        "wall_s": report["wall_s"],
        "far_deliveries": report["far_deliveries"],
        "far_barrier_rounds": report["far_barrier_rounds"],
        "trace_digest": report["trace_digest"],
        "sim_sharded_nodes_per_sec": round(rate, 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=200)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--table",
        action="store_true",
        help="run the docs/PERF.md scale ladder (50/200/1000) instead "
        "of one size",
    )
    parser.add_argument(
        "--far",
        action="store_true",
        help="run the 10k-node far-field per-shard ladder (1/2/4 "
        "shards; >1 = one OS process per shard) — the round-17 "
        "docs/PERF.md row; digests must agree across the ladder",
    )
    args = parser.parse_args()
    if args.far:
        digests = set()
        for shards in (1, 2, 4):
            row = bench_far_field(shards=shards, seed=args.seed)
            digests.add(row["trace_digest"])
            print(json.dumps(row))
        assert len(digests) == 1, "shard split moved the merged digest!"
    elif args.table:
        for n in (50, 200, 1000):
            print(json.dumps(bench_sim(n, args.seed)))
    else:
        print(json.dumps(bench_sim(args.nodes, args.seed)))


if __name__ == "__main__":
    main()
