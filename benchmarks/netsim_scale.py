"""Simulator scale benchmark: nodes x virtual-seconds per wall-second.

The question this answers: how much mesh can one host simulate, and how
fast?  The metric is ``sim_nodes_per_sec`` = nodes x virtual_seconds /
wall_seconds — node-seconds of simulated network per second of real
time — measured on the partition-heal scenario (the corpus flagship:
mesh formation, gossip, a 60/40 cut, divergent mining, mass reorg on
heal).  The scale table (``--table``) feeds docs/PERF.md; the single
default run feeds ``bench.py``'s ``sim_nodes_per_sec`` line against the
pinned ``RECORDED_SIM_RATE`` (p1_tpu/hashx/perf_record.py).

Real sockets on this 1-vCPU host topped out around 7 nodes at 1x real
time, i.e. ~7 node-seconds/second; the simulator's figure is the
multiple of that wall this round removed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_sim(nodes: int = 200, seed: int = 0) -> dict:
    """One partition-heal run; returns the rate figures + the report."""
    from p1_tpu.node.scenarios import partition_heal

    report = partition_heal(nodes=nodes, seed=seed)
    rate = nodes * report["virtual_s"] / max(report["wall_s"], 1e-9)
    return {
        "nodes": nodes,
        "ok": report["ok"],
        "virtual_s": report["virtual_s"],
        "wall_s": report["wall_s"],
        "events": report["events"],
        "events_per_wall_s": round(report["events"] / max(report["wall_s"], 1e-9)),
        "sim_nodes_per_sec": round(rate, 1),
    }


def bench_far_field(
    nodes: int = 10_000, shards: int = 1, seed: int = 0
) -> dict:
    """One far-field scenario run (full-node core + header-only far
    field, node/farfield.py) at ``shards`` — the round-17 per-shard
    scaling row.  Rate metric: node-seconds of simulated mesh per wall
    second over the whole composed run, same definition as
    ``bench_sim`` so the two tables read against each other.  Honesty:
    far-field node-seconds are HEADER-ONLY node-seconds (no mempool,
    ledger, stores, supervision — docs/PERF.md spells out the model),
    and on a 1-vCPU host process shards ADD overhead; the sharding is
    for multi-core hosts."""
    from p1_tpu.node.scenarios import far_field

    report = far_field(nodes=nodes, seed=seed, shards=shards)
    rate = nodes * report["virtual_s"] / max(report["wall_s"], 1e-9)
    return {
        "nodes": nodes,
        "shards": shards,
        "shard_processes": report["shard_processes"],
        "ok": report["ok"],
        "virtual_s": report["virtual_s"],
        "wall_s": report["wall_s"],
        "far_deliveries": report["far_deliveries"],
        "far_barrier_rounds": report["far_barrier_rounds"],
        "trace_digest": report["trace_digest"],
        "sim_sharded_nodes_per_sec": round(rate, 1),
    }


def bench_relay(nodes: int = 16, seed: int = 0, **kw) -> dict:
    """One relay-budget A/B run (flood arm vs reconciliation arm over
    the same shaped mesh — node/scenarios.py ``relay_budget``).  The
    two figures ``bench.py`` pins: ``relay_bytes_per_tx`` (recon arm,
    tx-plane bytes per delivered tx-node pair) and ``tx_prop_p95_ms``
    (recon arm, submit-to-everywhere p95).  ``reduction`` is the
    flood/recon byte ratio — the tentpole's ≥5x budget, judged by the
    scenario's own ``ok`` so the benchmark can't pass a run the
    acceptance gate would fail.  Extra kwargs pass through to the
    scenario: bench.py's quick probe shrinks the mesh and storm (and
    relaxes ``min_reduction`` to a 3x guard band — reduction grows
    with mesh size, and the quick mesh is smaller than the 16-node
    acceptance run this function defaults to)."""
    from p1_tpu.node.scenarios import relay_budget

    report = relay_budget(nodes=nodes, seed=seed, **kw)
    return {
        "nodes": nodes,
        "ok": report["ok"],
        "wall_s": report["wall_s"],
        "total_txs": report["total_txs"],
        "flood_bytes_per_tx": report["flood"]["bytes_per_tx"],
        "flood_p95_ms": report["flood"]["propagation"]["p95_ms"],
        "relay_bytes_per_tx": report["recon"]["bytes_per_tx"],
        "tx_prop_p95_ms": report["recon"]["propagation"]["p95_ms"],
        "reduction": report["reduction"],
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=200)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--table",
        action="store_true",
        help="run the docs/PERF.md scale ladder (50/200/1000) instead "
        "of one size",
    )
    parser.add_argument(
        "--far",
        action="store_true",
        help="run the 10k-node far-field per-shard ladder (1/2/4 "
        "shards; >1 = one OS process per shard) — the round-17 "
        "docs/PERF.md row; digests must agree across the ladder",
    )
    parser.add_argument(
        "--relay",
        action="store_true",
        help="run the 16-node relay-budget A/B (flood vs "
        "reconciliation over shaped uplinks) — the round-23 "
        "docs/PERF.md row and bench.py's relay_bytes_per_tx / "
        "tx_prop_p95_ms source",
    )
    args = parser.parse_args()
    if args.relay:
        print(json.dumps(bench_relay(seed=args.seed)))
    elif args.far:
        digests = set()
        for shards in (1, 2, 4):
            row = bench_far_field(shards=shards, seed=args.seed)
            digests.add(row["trace_digest"])
            print(json.dumps(row))
        assert len(digests) == 1, "shard split moved the merged digest!"
    elif args.table:
        for n in (50, 200, 1000):
            print(json.dumps(bench_sim(n, args.seed)))
    else:
        print(json.dumps(bench_sim(args.nodes, args.seed)))


if __name__ == "__main__":
    main()
