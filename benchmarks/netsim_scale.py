"""Simulator scale benchmark: nodes x virtual-seconds per wall-second.

The question this answers: how much mesh can one host simulate, and how
fast?  The metric is ``sim_nodes_per_sec`` = nodes x virtual_seconds /
wall_seconds — node-seconds of simulated network per second of real
time — measured on the partition-heal scenario (the corpus flagship:
mesh formation, gossip, a 60/40 cut, divergent mining, mass reorg on
heal).  The scale table (``--table``) feeds docs/PERF.md; the single
default run feeds ``bench.py``'s ``sim_nodes_per_sec`` line against the
pinned ``RECORDED_SIM_RATE`` (p1_tpu/hashx/perf_record.py).

Real sockets on this 1-vCPU host topped out around 7 nodes at 1x real
time, i.e. ~7 node-seconds/second; the simulator's figure is the
multiple of that wall this round removed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_sim(nodes: int = 200, seed: int = 0) -> dict:
    """One partition-heal run; returns the rate figures + the report."""
    from p1_tpu.node.scenarios import partition_heal

    report = partition_heal(nodes=nodes, seed=seed)
    rate = nodes * report["virtual_s"] / max(report["wall_s"], 1e-9)
    return {
        "nodes": nodes,
        "ok": report["ok"],
        "virtual_s": report["virtual_s"],
        "wall_s": report["wall_s"],
        "events": report["events"],
        "events_per_wall_s": round(report["events"] / max(report["wall_s"], 1e-9)),
        "sim_nodes_per_sec": round(rate, 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=200)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--table",
        action="store_true",
        help="run the docs/PERF.md scale ladder (50/200/1000) instead "
        "of one size",
    )
    args = parser.parse_args()
    if args.table:
        for n in (50, 200, 1000):
            print(json.dumps(bench_sim(n, args.seed)))
    else:
        print(json.dumps(bench_sim(args.nodes, args.seed)))


if __name__ == "__main__":
    main()
