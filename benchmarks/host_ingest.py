"""Host ingest benchmark: the serialization plane, measured end-to-end.

The Pallas kernel already runs at the v5e VPU issue-rate wall
(docs/PERF.md "VPU roofline"), so the system's remaining headroom is the
*host* plane: gossip ingest → validate → ``add_block`` → store append →
relay, and the resume/replay paths.  This harness measures exactly those,
with the same contract as ``bench.py``: print ONE JSON line, measured on
this machine, no estimates.

Three measurements:

- **ingest** — blocks/s through the object-plane pipeline a gossip frame
  pays: ``Block.deserialize(wire bytes)`` → ``Chain.add_block`` (which
  runs the full stateless ``check_block`` + connect-time ledger).
  Ed25519 signature verification is warmed first and stated in the
  output: mempool admission has already verified every transfer a block
  carries by the time the block arrives (``keys.verify`` memoizes), so
  the steady-state ingest cost is the serialization/hashing plane, not
  signature math — exactly what this harness isolates.
- **resume** — blocks/s through ``ChainStore.load_chain(trusted=True)``
  from a real on-disk store: the node-restart path (parse + index +
  ledger bookkeeping, docs/PERF.md "Restart at scale").
- **staged ingest** (``--cores``, opt-in) — blocks/s through the
  round-19 staged pipeline (node/pipeline.py): deserialize on the loop,
  batched Ed25519 pre-verification on the validate lane, ``add_block``
  on the loop, fsynced store append on the store lane, with 1-deep
  stage overlap.  Run as a ladder (``--cores 1,2,4``) it emits the
  scaling row plus an unstaged same-driver control, so both acceptance
  claims — multi-core speedup and ≤5% single-core overhead — are
  measured numbers.
- **replay** — headers/s verifying a mined header chain from
  ``BlockHeader`` objects (``replay_fast`` — the native engine when it
  builds, else the hashlib oracle), plus the hashlib oracle and the
  pre-packed native ceiling for context.  Encodings are warmed before
  the timed run: the object-plane figure models a node replaying headers
  it already holds (ingested off the wire or serialized once), which is
  how every real caller reaches this path.

Runs anywhere (``JAX_PLATFORMS=cpu``, no TPU, no network); difficulty 1
keeps mining the fixtures cheap while exercising real PoW checks.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

# Runnable as `python benchmarks/host_ingest.py` from a checkout, like
# bench.py — the repo root is the import root.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_blocks(n_blocks: int, txs_per_block: int, difficulty: int):
    """Mine a valid n-block chain carrying signed transfers; return the
    wire bytes of every post-genesis block (what gossip would deliver)."""
    from p1_tpu.chain.chain import Chain
    from p1_tpu.core.block import Block, merkle_root
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    alice = Keypair.from_seed_text("host-ingest-alice")
    chain = Chain(difficulty)
    tag = chain.genesis.block_hash()
    miner = Miner(backend=get_backend("cpu"))
    raws: list[bytes] = []
    seq = 0
    for height in range(1, n_blocks + 1):
        txs = [Transaction.coinbase(alice.account, height)]
        # Transfers only once the ledger can afford them (coinbase at
        # height h is spendable from height h+1's perspective here since
        # the ledger credits on connect).
        if height > 1:
            for _ in range(txs_per_block):
                txs.append(
                    Transaction.transfer(alice, "bob", 1, 1, seq, chain=tag)
                )
                seq += 1
        parent = chain.tip
        draft = BlockHeader(
            version=1,
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=parent.header.timestamp + height,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        assert sealed is not None
        block = Block(sealed, tuple(txs))
        res = chain.add_block(block)
        assert res.status.value == "accepted", res
        raws.append(block.serialize())
    return chain, raws


def bench_ingest(raws: list[bytes], difficulty: int, repeats: int) -> float:
    """Best-of-N blocks/s: deserialize -> full-validation add_block."""
    from p1_tpu.chain.chain import AddStatus, Chain
    from p1_tpu.core.block import Block

    best = 0.0
    for _ in range(repeats):
        chain = Chain(difficulty)
        t0 = time.perf_counter()
        for raw in raws:
            res = chain.add_block(Block.deserialize(raw))
            assert res.status is AddStatus.ACCEPTED
        dt = time.perf_counter() - t0
        best = max(best, len(raws) / dt)
    return best


async def _staged_drive(
    raws: list[bytes], difficulty: int, cores: int, path: Path
) -> float:
    """One staged-ingest pass: the node's pipeline shape, blocks/s.

    Drives the round-19 stage split exactly as ``Node._handle_block``
    does — deserialize on the loop (frame stage), batched Ed25519
    pre-verification on the validate lane, ``add_block`` on the loop,
    fsynced append on the store lane — with the 1-deep overlap the
    real node gets for free from its peer coroutines: validate(i+1)
    and store(i) run on their lanes while connect(i) runs on the loop.
    ``cores == 0`` runs the identical driver through the inline
    (unstaged) pipeline, so the rung-0 figure IS the staging overhead
    control.  A fresh SignatureCache per pass means the validate stage
    pays real signature math every run (the serial ``ingest`` figure
    above deliberately warms it away; this one deliberately does not —
    the verify pool is where extra cores go to work).
    """
    import asyncio

    from p1_tpu.chain.chain import AddStatus, Chain
    from p1_tpu.chain.store import ChainStore
    from p1_tpu.chain.validate import preverify_signatures
    from p1_tpu.core.block import Block
    from p1_tpu.core.sigcache import SignatureCache
    from p1_tpu.node.pipeline import NodePipeline

    cache = SignatureCache()
    chain = Chain(difficulty)
    chain.sig_cache = cache
    tag = chain.genesis.block_hash()
    store = ChainStore(path, fsync=True)
    pipeline = NodePipeline(workers=cores)

    async def validate(idx: int):
        block = Block.deserialize(raws[idx])
        await pipeline.run_validate(
            preverify_signatures,
            block.txs,
            tag,
            cache,
            nbytes=len(raws[idx]),
        )
        return block

    try:
        t0 = time.perf_counter()
        # Store jobs ride the lane's FIFO — submission order IS append
        # order — so the loop only back-pressures at a bounded depth
        # instead of paying a loop<->lane round trip per block.
        store_jobs: list = []
        nxt = asyncio.ensure_future(validate(0))
        for i in range(len(raws)):
            block = await nxt
            if i + 1 < len(raws):
                nxt = asyncio.ensure_future(validate(i + 1))
            res = chain.add_block(block)
            assert res.status is AddStatus.ACCEPTED, res
            if len(store_jobs) >= 8:
                await store_jobs.pop(0)
            store_jobs.append(
                asyncio.ensure_future(
                    pipeline.run_store(
                        store.append, block, nbytes=len(raws[i])
                    )
                )
            )
        for job in store_jobs:
            await job
        dt = time.perf_counter() - t0
    finally:
        pipeline.drain_and_close()
        store.close()
    assert chain.height == len(raws)
    return len(raws) / dt


def bench_staged_ingest(
    raws: list[bytes],
    difficulty: int,
    cores_ladder: list[int],
    repeats: int,
    tmpdir: str,
) -> dict:
    """Best-of-N staged blocks/s per rung of the cores ladder, plus the
    unstaged (cores=0) control through the same driver."""
    import asyncio

    from p1_tpu.core import keys

    out: dict = {}
    prev_workers = keys.verify_workers()
    run = 0
    try:
        for cores in [0, *cores_ladder]:
            # Mirror Node.__init__: the pipeline worker count sizes the
            # Ed25519 verify pool — the lane thread fans each preverify
            # batch across that many cores.
            keys.set_verify_workers(cores)
            best = 0.0
            for _ in range(repeats):
                run += 1
                path = Path(tmpdir) / f"staged_{cores}_{run}.chain"
                bps = asyncio.run(
                    _staged_drive(raws, difficulty, cores, path)
                )
                best = max(best, bps)
            out[cores] = best
    finally:
        keys.set_verify_workers(prev_workers)
    return out


def bench_resume(
    raws: list[bytes], difficulty: int, repeats: int, tmpdir: str
) -> float:
    """Best-of-N blocks/s through the trusted-resume path from disk."""
    from p1_tpu.chain.store import ChainStore
    from p1_tpu.core.block import Block

    path = Path(tmpdir) / "ingest_bench.chain"
    store = ChainStore(path, fsync=False)
    try:
        for raw in raws:
            store.append(Block.deserialize(raw))
    finally:
        store.close()
    best = 0.0
    for _ in range(repeats):
        store = ChainStore(path, fsync=False)
        try:
            t0 = time.perf_counter()
            chain = store.load_chain(difficulty, trusted=True)
            dt = time.perf_counter() - t0
        finally:
            store.close()
        assert chain.height == len(raws)
        best = max(best, len(raws) / dt)
    return best


def bench_replay(n_headers: int, repeats: int) -> dict:
    """Headers/s from objects (replay_fast), the hashlib oracle, and the
    pre-packed native ceiling (when the native engine builds)."""
    from p1_tpu.chain.replay import generate_headers, replay_fast, replay_host

    headers = generate_headers(n_headers, difficulty=1)
    for h in headers:  # warm encodings: the as-held-by-a-node plane
        h.serialize()
    out: dict = {"replay_n": n_headers}
    best = 0.0
    for _ in range(repeats):
        report = replay_fast(headers)
        assert report.valid
        best = max(best, report.headers_per_sec)
    out["replay_object_hps"] = round(best)
    out["replay_method"] = report.method
    best = 0.0
    for _ in range(repeats):
        host = replay_host(headers)
        assert host.valid
        best = max(best, host.headers_per_sec)
    out["replay_host_hps"] = round(best)
    try:
        from p1_tpu.hashx.native_backend import verify_header_chain

        raw = b"".join(h.serialize() for h in headers)
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            assert verify_header_chain(raw, len(headers), 1) is None
            dt = time.perf_counter() - t0
            best = max(best, len(headers) / dt)
        out["replay_native_raw_hps"] = round(best)
    except Exception:  # no toolchain: the ceiling row is simply absent
        pass
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=1000)
    ap.add_argument("--txs", type=int, default=2, help="transfers per block")
    ap.add_argument("--replay-n", type=int, default=20_000)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument(
        "--cores",
        default=None,
        help="staged-ingest mode: a worker count (`4`) or a scaling "
        "ladder (`1,2,4`) for the round-19 pipeline; each rung runs "
        "the staged driver with that many pipeline workers (and a "
        "matching verify pool), plus an unstaged cores=0 control",
    )
    args = ap.parse_args(argv)

    from p1_tpu.core import keys

    difficulty = 1
    chain, raws = build_blocks(args.blocks, args.txs, difficulty)
    # Warm the signature memo (the mempool-admission state a block meets).
    for block in chain.main_chain():
        for tx in block.txs:
            assert tx.verify_signature()

    ingest_bps = bench_ingest(raws, difficulty, args.repeats)
    staged: dict = {}
    with tempfile.TemporaryDirectory() as tmpdir:
        resume_bps = bench_resume(raws, difficulty, args.repeats, tmpdir)
        if args.cores:
            ladder = sorted(
                {int(c) for c in str(args.cores).split(",") if int(c) > 0}
            )
            rungs = bench_staged_ingest(
                raws, difficulty, ladder, args.repeats, tmpdir
            )
            from p1_tpu.hashx.perf_record import RECORDED_STAGED_INGEST_BPS

            top = ladder[-1]
            unstaged = rungs[0]
            staged = {
                "staged_cores": top,
                "staged_ingest_bps": round(rungs[top], 1),
                "staged_ingest_vs_recorded": round(
                    rungs[top] / RECORDED_STAGED_INGEST_BPS, 2
                ),
                # The 1→2→4 scaling row (whatever rungs were asked for),
                # plus the same-driver unstaged control so the staging
                # overhead claim is measured, not asserted.
                "staged_scaling_bps": {
                    str(c): round(rungs[c], 1) for c in ladder
                },
                "unstaged_driver_bps": round(unstaged, 1),
            }
            if 1 in ladder and unstaged > 0:
                staged["staged_1core_overhead_pct"] = round(
                    (unstaged - rungs[1]) / unstaged * 100.0, 1
                )
    replay = bench_replay(args.replay_n, args.repeats)

    from p1_tpu.hashx.perf_record import RECORDED_HOST_INGEST_BPS

    print(
        json.dumps(
            {
                "metric": "host_ingest_blocks_per_sec",
                "value": round(ingest_bps, 1),
                "unit": "blocks/s",
                "vs_recorded": round(
                    ingest_bps / RECORDED_HOST_INGEST_BPS, 2
                ),
                "n_blocks": args.blocks,
                "txs_per_block": args.txs,
                "resume_bps": round(resume_bps, 1),
                "sig_backend": (
                    "cryptography" if keys.HAVE_CRYPTOGRAPHY else "rfc8032-py"
                ),
                **staged,
                **replay,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
