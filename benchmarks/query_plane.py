"""Query-plane benchmark: the read serving tier, measured host-side.

ROADMAP open item 1's "done" bar: a queries/s figure for the serving
plane — inclusion proofs above all — with the serial-per-proof baseline
measured in the SAME run so the speedup table is honest (the bench.py
contract: one JSON line, measured, no estimates).

Measurements (all on one fixture chain of ``--blocks`` blocks carrying
``--txs`` signed transfers each):

- **proof_serial_qps** — the pre-round-9 baseline: every proof rebuilt
  from scratch (txid list + full merkle branch reconstruction per
  query, cache disabled) and wire-encoded, exactly what GETPROOF cost
  before this tier existed.
- **proof_batched_qps** — cold proof cache, queries clustered by block:
  one merkle-tree construction amortized across every transaction of a
  block (chain/proof.py ``build_block_proofs``), wire-encode included.
- **proof_cached_qps** — steady state: the bounded LRU holds the
  serialized payloads, each serve is a dict hit plus the 4-byte
  tip-height patch (protocol.patch_proof_tip).  This is the figure the
  ≥50k/s target reads against — it is what a replica worker's hot loop
  does per query, and it multiplies across `p1 serve` processes.
- **filter_build_bps / filter_match_bps** — blocks/s building compact
  filters (the connect-time cost) and matching a wallet's watch set
  against a prebuilt filter stream (the light-client download loop),
  plus filter bytes/block (the light client's bandwidth price).
- **replica_index_bps** — blocks/s through ``ReplicaView`` attach (the
  mmap scan + txid index a `p1 serve` worker pays once at startup).

JSON keys: {"metric": "proof_cached_qps", "value": ..., ...} with the
serial/batched/filter figures as extra keys; ``vs_serial`` is the
headline speedup (cached / serial).
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_chain(n_blocks: int, txs_per_block: int, difficulty: int = 1):
    """A valid chain with signed transfers (same fixture recipe as
    benchmarks/host_ingest.py)."""
    from p1_tpu.chain.chain import Chain
    from p1_tpu.core.block import Block, merkle_root
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    alice = Keypair.from_seed_text("query-plane-alice")
    chain = Chain(difficulty)
    tag = chain.genesis.block_hash()
    miner = Miner(backend=get_backend("cpu"))
    seq = 0
    for height in range(1, n_blocks + 1):
        txs = [Transaction.coinbase(alice.account, height)]
        if height > 1:
            for _ in range(txs_per_block):
                txs.append(
                    Transaction.transfer(alice, "bob", 1, 1, seq, chain=tag)
                )
                seq += 1
        parent = chain.tip
        draft = BlockHeader(
            version=1,
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=parent.header.timestamp + 60,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        assert sealed is not None
        res = chain.add_block(Block(sealed, tuple(txs)))
        assert res.status.value == "accepted", res
    return chain


def _transfer_txids(chain) -> list[bytes]:
    out = []
    for block in chain.main_chain():
        for tx in block.txs:
            if not tx.is_coinbase:
                out.append(tx.txid())
    return out


def bench_proofs(chain, txids, repeats: int = 3) -> dict:
    """serial / batched / cached proofs-per-second over ``txids``."""
    from p1_tpu.chain.proof import ProofCache
    from p1_tpu.core.block import merkle_branch
    from p1_tpu.chain.proof import TxProof
    from p1_tpu.node import protocol

    # Serial baseline: the pre-cache GETPROOF path — txid index lookup,
    # whole-block txid list, O(ntx) merkle branch, fresh encode.  Kept
    # inline (not Chain.tx_proof, which now batches by design) so the
    # baseline stays measurable forever.
    def serial_one(txid: bytes) -> bytes:
        bhash = chain._tx_index[txid]
        entry = chain._index[bhash]
        block = chain._block_at(bhash)
        tids = [tx.txid() for tx in block.txs]
        index = tids.index(txid)
        proof = TxProof(
            tx=block.txs[index],
            header=block.header,
            height=entry.height,
            tip_height=chain.height,
            index=index,
            branch=merkle_branch(tids, index),
        )
        return protocol.encode_proof(proof)

    sample = txids[: min(len(txids), 2000)]
    best_serial = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for txid in sample:
            serial_one(txid)
        dt = time.perf_counter() - t0
        best_serial = max(best_serial, len(sample) / dt)

    def payload(txid: bytes) -> bytes:
        entry = chain.tx_proof_entry(txid)
        if entry.payload is None:
            chain.proof_cache.note_payload(
                entry, protocol.encode_proof(entry.proof)
            )
        return protocol.patch_proof_tip(entry.payload, chain.height)

    # Batched: cold cache each repeat, every transfer proof cut once —
    # the first-touch cost of a block's whole proof set.
    best_batched = 0.0
    for _ in range(repeats):
        chain.proof_cache = ProofCache(max_bytes=256 << 20)
        t0 = time.perf_counter()
        for txid in txids:
            payload(txid)
        dt = time.perf_counter() - t0
        best_batched = max(best_batched, len(txids) / dt)

    # Cached: steady state over the warm LRU (the previous loop warmed
    # payloads too).
    best_cached = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for txid in txids:
            payload(txid)
        dt = time.perf_counter() - t0
        best_cached = max(best_cached, len(txids) / dt)

    return {
        "proof_serial_qps": round(best_serial),
        "proof_batched_qps": round(best_batched),
        "proof_cached_qps": round(best_cached),
        "proofs_sampled": len(txids),
    }


def bench_filters(chain, repeats: int = 3) -> dict:
    """Filter build + match rates and the bytes/block price."""
    from p1_tpu.chain import filters

    blocks = list(chain.main_chain())[1:]
    best_build = 0.0
    built: list[tuple[bytes, bytes]] = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        built = [(b.block_hash(), filters.block_filter(b)) for b in blocks]
        dt = time.perf_counter() - t0
        best_build = max(best_build, len(blocks) / dt)
    watch = [b"bob", b"nobody-watches-this-account"]
    best_match = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        hits = sum(
            1
            for bhash, f in built
            if filters.matches_any(f, bhash, watch)
        )
        dt = time.perf_counter() - t0
        best_match = max(best_match, len(built) / dt)
    total_bytes = sum(len(f) for _, f in built)
    return {
        "filter_build_bps": round(best_build),
        "filter_match_bps": round(best_match),
        "filter_bytes_per_block": round(total_bytes / max(1, len(built)), 1),
        "filter_matched_blocks": hits,
    }


def bench_replica(chain, difficulty: int) -> dict:
    """ReplicaView attach rate (mmap scan + txid index) from a real
    on-disk store of the fixture chain."""
    from p1_tpu.chain.store import save_chain
    from p1_tpu.node.queryplane import ReplicaView

    with tempfile.TemporaryDirectory() as tmp:
        store = Path(tmp) / "chain.dat"
        save_chain(chain, store)
        t0 = time.perf_counter()
        view = ReplicaView(store, difficulty)
        dt = time.perf_counter() - t0
        assert view.tip_height == chain.height
        view.close()
        return {
            "replica_index_bps": round((chain.height + 1) / dt),
        }


def bench_quick(blocks: int = 60, txs: int = 24, repeats: int = 3) -> dict:
    """The bench.py hook: a small same-session measurement of the three
    proof rates (serial baseline included, same run)."""
    chain = build_chain(blocks, txs, difficulty=1)
    txids = _transfer_txids(chain)
    return bench_proofs(chain, txids, repeats=repeats)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", type=int, default=120)
    ap.add_argument("--txs", type=int, default=48, help="transfers per block")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    chain = build_chain(args.blocks, args.txs, difficulty=1)
    txids = _transfer_txids(chain)
    proofs = bench_proofs(chain, txids, repeats=args.repeats)
    filt = bench_filters(chain, repeats=args.repeats)
    replica = bench_replica(chain, difficulty=1)

    import os

    try:
        load_1m, load_5m, _ = os.getloadavg()
    except OSError:
        load_1m = load_5m = None

    print(
        json.dumps(
            {
                "metric": "proof_cached_qps",
                "value": proofs["proof_cached_qps"],
                "unit": "proofs/s",
                "vs_serial": round(
                    proofs["proof_cached_qps"]
                    / max(1, proofs["proof_serial_qps"]),
                    1,
                ),
                "batched_vs_serial": round(
                    proofs["proof_batched_qps"]
                    / max(1, proofs["proof_serial_qps"]),
                    1,
                ),
                "blocks": args.blocks,
                "txs_per_block": args.txs,
                "load_avg_1m": load_1m,
                "load_avg_5m": load_5m,
                "cpu_count": os.cpu_count(),
                **proofs,
                **filt,
                **replica,
            }
        )
    )


if __name__ == "__main__":
    main()
