"""Ledger scale benchmark: the in-RAM account map at 1M accounts.

ROADMAP item 2's pairing: snapshot sync makes the LEDGER the thing a
new node downloads, so its in-RAM representation becomes a first-class
scale surface — measure it the way PR 4 measured the block index
(docs/PERF.md "Memory-bounded operation").

Two candidate representations, measured head to head on this host:

- **two-dict** (the shipped ``Ledger``): ``balances: dict[str, int]`` +
  ``nonces: dict[str, int]``.  Costs the key string twice for accounts
  that carry both, but values are bare ints and accounts without
  nonces (most of them — only SENDERS have nonces) pay one entry.
- **slotted-entry**: one ``dict[str, _Account]`` with
  ``__slots__ = ("balance", "nonce")``.  One key per account, but a
  56-byte object shell per entry where the two-dict pays ~28 bytes of
  int — the classic space trade the measurement settles.

Reported per representation: RSS growth building N accounts (VmRSS
delta — the honest whole-process figure), per-lookup latency over
random accounts, and per-block apply latency (``Ledger.apply_block``
with a transfer-carrying block) for the shipped form.  One JSON line;
the docs/PERF.md table comes straight from a run of this file.
"""

from __future__ import annotations

import argparse
import gc
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _vm_rss() -> int:
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    raise RuntimeError("no VmRSS")


class _Account:
    __slots__ = ("balance", "nonce")

    def __init__(self, balance: int, nonce: int):
        self.balance = balance
        self.nonce = nonce


def _accounts(n: int):
    return [f"acct-{i:09d}" for i in range(n)]


def bench_two_dict(names, sender_frac: float, rng) -> dict:
    gc.collect()
    rss0 = _vm_rss()
    balances: dict[str, int] = {}
    nonces: dict[str, int] = {}
    for name in names:
        balances[name] = 100
        if rng.random() < sender_frac:
            nonces[name] = 3
    gc.collect()
    grew = _vm_rss() - rss0
    probe = rng.sample(names, min(100_000, len(names)))
    t0 = time.perf_counter()
    acc = 0
    for name in probe:
        acc += balances.get(name, 0) + nonces.get(name, 0)
    dt = time.perf_counter() - t0
    assert acc > 0
    out = {
        "rss_bytes": grew,
        "bytes_per_account": round(grew / len(names), 1),
        "lookup_ns": round(1e9 * dt / len(probe), 1),
    }
    del balances, nonces
    return out


def bench_slotted(names, sender_frac: float, rng) -> dict:
    gc.collect()
    rss0 = _vm_rss()
    table: dict[str, _Account] = {}
    for name in names:
        table[name] = _Account(100, 3 if rng.random() < sender_frac else 0)
    gc.collect()
    grew = _vm_rss() - rss0
    probe = rng.sample(names, min(100_000, len(names)))
    t0 = time.perf_counter()
    acc = 0
    for name in probe:
        entry = table.get(name)
        if entry is not None:
            acc += entry.balance + entry.nonce
    dt = time.perf_counter() - t0
    assert acc > 0
    out = {
        "rss_bytes": grew,
        "bytes_per_account": round(grew / len(names), 1),
        "lookup_ns": round(1e9 * dt / len(probe), 1),
    }
    del table
    return out


def bench_apply(n_accounts: int, rng) -> dict:
    """Per-block ledger apply/undo on the SHIPPED Ledger with the map
    pre-grown to ``n_accounts`` — the latency a tip move pays at scale."""
    from p1_tpu.chain.ledger import Ledger
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction

    ledger = Ledger.restore(
        {f"acct-{i:09d}": 100 for i in range(n_accounts)}, {}
    )
    alice = Keypair.from_seed_text("ledger-scale-alice")
    ledger._balances[alice.account] = 10_000

    class _FakeBlock:
        def __init__(self, txs):
            self.txs = txs

    rounds = 200
    t0 = time.perf_counter()
    for i in range(rounds):
        txs = [Transaction.coinbase("miner", i + 1)]
        for j in range(4):
            txs.append(
                Transaction(
                    sender=alice.account,
                    recipient=f"acct-{rng.randrange(n_accounts):09d}",
                    amount=1,
                    fee=0,
                    # Each round is undone, so the nonce rewinds too.
                    seq=j,
                )
            )
        block = _FakeBlock(tuple(txs))
        ledger.apply_block(block)
        ledger.undo_block(block)
    dt = time.perf_counter() - t0
    return {"apply_undo_us_per_block": round(1e6 * dt / rounds, 1)}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--accounts", type=int, default=1_000_000)
    ap.add_argument(
        "--sender-frac",
        type=float,
        default=0.1,
        help="fraction of accounts that also carry a nonce entry",
    )
    args = ap.parse_args()
    rng = random.Random(0)
    names = _accounts(args.accounts)
    # Two-dict FIRST, slotted second, each measured as RSS growth from
    # its own baseline; the name list is shared (and excluded from both
    # growth figures by construction).
    two = bench_two_dict(names, args.sender_frac, random.Random(1))
    gc.collect()
    slotted = bench_slotted(names, args.sender_frac, random.Random(1))
    apply_stats = bench_apply(min(args.accounts, 1_000_000), rng)
    print(
        json.dumps(
            {
                "config": "ledger_scale",
                "accounts": args.accounts,
                "sender_frac": args.sender_frac,
                "two_dict": two,
                "slotted": slotted,
                **apply_stats,
                "winner": (
                    "two_dict"
                    if two["rss_bytes"] <= slotted["rss_bytes"]
                    else "slotted"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
