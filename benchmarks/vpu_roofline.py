"""VPU uint32 roofline for the Pallas SHA-256d search kernel.

Two measurements feed docs/PERF.md's "Roofline" section (VERDICT r4
item 3 asked for the denominator behind the MH/s headline):

1. ``--count``: a static op count of one SHA-256d candidate exactly as
   the kernel traces it (jax_sha256._compress round body), twice — naive
   "as written" (every shift/or/xor/add/and/not = 1), and a fold model
   where compile-time-constant subtrees fold away and all-scalar ops run
   on the scalar core instead of the VPU.  No hardware needed.

2. default: a Pallas microbenchmark measuring the VPU's achievable
   uint32 ALU rate with op mixes from pure adds to full SHA-round-like
   bodies.  Chains are mutually recursive (unfoldable), per-lane varying
   (unscalarizable), and grid-index-seeded (unhoistable) — each of those
   was observed to be silently optimized away without the countermeasure,
   inflating rates ~500x.  Run on the TPU: ``python benchmarks/
   vpu_roofline.py``.  Timing caveats on the axon relay (measured, not
   theoretical): ``block_until_ready`` does NOT reliably block — a call
   can "complete" in ~0.1 ms with the value only materializing at the
   first host readback — and repeat executions with identical input
   buffers return instantly (served from somewhere short of the chip).
   So every timed repetition here uses FRESH input values and times a
   forced ``int()`` scalar readback; each dispatch then carries
   ~0.06-0.1 s of RPC latency on top of compute, so configs are sized
   to ~0.4 s compute and the compute-only rate subtracts the dispatch.
"""

from __future__ import annotations

import argparse
import functools
import statistics
import time


# ---------------------------------------------------------------- op count

def count_ops() -> dict:
    """Static per-candidate op counts of the traced kernel math."""

    class T:  # tagged operand: C compile-time const, S scalar, V vector
        def __init__(self, kind):
            self.kind = kind

    count = {"V": 0, "S": 0, "naive": 0}

    def op(*args):
        count["naive"] += 1
        kinds = {a.kind for a in args}
        if kinds == {"C"}:
            return T("C")  # folds at compile time
        if "V" in kinds:
            count["V"] += 1
            return T("V")
        count["S"] += 1  # scalar-core op, off the VPU
        return T("S")

    def rotr(x):  # two shifts + or, as _rotr writes it
        return op(op(x), op(x))

    def xor3(a, b, c):
        return op(op(a, b), c)

    def compress(state, w):
        s, w = list(state), list(w)
        for _ in range(64):
            a, b, c, d, e, f, g, h = s
            s1 = xor3(rotr(e), rotr(e), rotr(e))
            ch = op(op(e, f), op(op(e), g))  # (e&f) ^ (~e & g)
            t1 = op(op(op(op(h, s1), ch), T("C")), w[0])  # + k + w0
            s0 = xor3(rotr(a), rotr(a), rotr(a))
            maj = xor3(op(a, b), op(a, c), op(b, c))
            sig0 = xor3(rotr(w[1]), rotr(w[1]), op(w[1]))
            sig1 = xor3(rotr(w[14]), rotr(w[14]), op(w[14]))
            w_next = op(op(op(w[0], sig0), w[9]), sig1)
            s = [op(op(t1, s0), maj), a, b, c, op(d, t1), e, f, g]
            w = w[1:] + [w_next]
        return [op(x, y) for x, y in zip(state, s)]

    # Pass 1 chunk 2: midstate/tail are runtime scalars, nonce is the one
    # vector input, padding/length are constants.
    state1 = compress(
        [T("S")] * 8, [T("S")] * 3 + [T("V")] + [T("C")] * 12
    )
    # Pass 2: the digest words are vectors, padding constants, IV constant.
    digest = compress([T("C")] * 8, state1 + [T("C")] * 8)
    # Target check (below_target): per word cmp, and, or, cmp, and.
    for d in digest:
        op(d, T("S")), op(T("V")), op(T("V")), op(d, T("S")), op(T("V"))
    for _ in range(6):  # flat-nonce computation + where/min plumbing
        op(T("V"))
    return count


# ------------------------------------------------------------- microbench

SUB = 16  # tile rows, same as the search kernel
OPS_PER_ITER = {"add": 2, "rot": 8, "sha": 11, "round": 30}


def _bench_kernel(seed_ref, out_ref, *, iters, chains, mix):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    U32 = jnp.uint32
    rows = jax.lax.broadcasted_iota(U32, (SUB, 128), 0)
    cols = jax.lax.broadcasted_iota(U32, (SUB, 128), 1)
    lane = rows * U32(128) + cols
    gi = pl.program_id(0).astype(U32)
    xs = [seed_ref[j] + lane + gi * U32(0x85EBCA6B) for j in range(chains)]
    ys = [
        (seed_ref[j] ^ U32(0x9E3779B9)) + (lane ^ gi) * U32(2654435761)
        for j in range(chains)
    ]

    def rot(v, n):
        return (v >> U32(n)) | (v << U32(32 - n))

    def one(x, y):
        if mix == "add":  # 2 ops/iter
            x = x + y
            y = y ^ x
        elif mix == "rot":  # 8 ops/iter
            x = rot(x, 7) ^ rot(y, 18)
            y = y + x
        elif mix == "sha":  # σ0-like, 11 ops/iter
            s = rot(x, 7) ^ rot(x, 18) ^ (x >> U32(3))
            x = s ^ y
            y = y + x
        elif mix == "round":  # SHA-round-like body, 30 ops/iter
            s1 = rot(x, 6) ^ rot(x, 11) ^ rot(x, 25)
            ch = (x & y) ^ (~x & (y + U32(1)))
            t1 = y + s1 + ch + U32(0x428A2F98)
            s0 = rot(t1, 2) ^ rot(t1, 13) ^ rot(t1, 22)
            x = t1 + s0
            y = y ^ x
        return x, y

    INNER = 16  # python-unrolled (Mosaic fori_loop: unroll=1 or full only)

    def body(_, carry):
        xs, ys = carry
        for _ in range(INNER):
            pairs = [one(x, y) for x, y in zip(xs, ys)]
            xs, ys = [p[0] for p in pairs], [p[1] for p in pairs]
        return xs, ys

    xs, ys = jax.lax.fori_loop(0, iters // INNER, body, (xs, ys), unroll=1)
    acc = xs[0]
    for v in xs[1:] + ys:
        acc = acc ^ v
    red = jnp.min(acc.astype(jnp.int32))
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0] = jnp.int32(0)

    out_ref[0] = out_ref[0] ^ red


@functools.cache
def _make_bench(grid, iters, chains, mix):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kern = functools.partial(
        _bench_kernel, iters=iters, chains=chains, mix=mix
    )
    call = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
    )
    return jax.jit(lambda s: call(s))


def run_bench() -> None:
    import jax
    import jax.numpy as jnp

    print("backend:", jax.default_backend(), jax.devices()[0])

    def measure(mix, chains, grid=512, reps=3):
        ops = OPS_PER_ITER[mix]
        iters = max(
            256, int(2.0e12 / (grid * chains * SUB * 128 * ops)) // 16 * 16
        )
        fn = _make_bench(grid, iters, chains, mix)
        base = jnp.arange(1, chains + 1, dtype=jnp.uint32) * jnp.uint32(
            0x01000193
        )
        int(fn(base)[0])  # compile + warm, forced readback
        best = 1e9
        for k in range(reps):
            seeds = base + jnp.uint32(k + 1)  # fresh values every rep
            t0 = time.perf_counter()
            int(fn(seeds)[0])  # timing a forced readback, see module doc
            best = min(best, time.perf_counter() - t0)
        rate = grid * iters * chains * SUB * 128 * ops / best
        return rate, best

    print(f"{'mix':>6} {'chains':>6} {'wall_s':>7} {'Top/s wall':>11}")
    rates = []
    for mix in ("add", "rot", "sha", "round"):
        for chains in (4, 8):
            rate, t = measure(mix, chains)
            rates.append(rate)
            print(f"{mix:>6} {chains:>6} {t:7.3f} {rate/1e12:11.2f}")
    med = statistics.median(rates)
    print(f"\nmedian wall rate: {med/1e12:.2f} Top/s "
          f"(compute-only ≈ wall × wall_s/(wall_s - dispatch); "
          f"dispatch ≈ 0.06-0.10 s on the axon relay)")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--count", action="store_true", help="op count only")
    args = ap.parse_args()
    c = count_ops()
    print(
        f"per-candidate SHA-256d ops as traced: naive {c['naive']} "
        f"(every shift/or/xor/add/and/not = 1); fold model: "
        f"{c['V']} vector ops on the VPU + {c['S']} scalar-core ops"
    )
    if not args.count:
        run_bench()
