"""Read-only replica workers: the query serving plane (`p1 serve`).

The scaling problem (ROADMAP open item 1): every headers/filters/proof
query a node answers runs on its single consensus asyncio thread — the
same thread that validates blocks, settles reorgs, and feeds the miner.
Query fan-out therefore could not scale past one core, and a heavy read
load was indistinguishable from an attack.  This module moves the READ
side of the protocol into separate processes that share nothing with
the consensus loop but the append-only store file itself:

- **No writer flock, ever.**  A replica opens the store read-only and
  never calls ``ChainStore.acquire`` — the live node (or ``p1 fsck`` /
  ``p1 compact``) keeps exclusive writership, and any number of
  replicas attach concurrently.  The append-only discipline is what
  makes this safe: a record, once checksum-valid at offset X, never
  changes (heals/compactions REPLACE the inode, which the replica
  detects by ``st_ino`` and handles by a clean rescan).

- **mmap + incremental tail scan.**  The file is memory-mapped; the v3
  checksum framing (chain/store.py) is walked once at attach and then
  only over the newly appended tail on each ``refresh()`` — headers
  are served as raw 80-byte mmap slices (no object parse:
  ``protocol.encode_headers_raw``), block bodies as raw record slices,
  and the per-record work is three SHA-256d digests per transaction at
  attach time (txid index) plus fork choice over header fields.  A
  torn tail (the writer's in-flight record) simply fails its CRC and
  is retried on the next refresh.

- **The same serving caches as the node.**  Proofs go through a
  ``ProofCache`` (chain/proof.py — whole-block merkle amortization +
  serialized-payload memoization + 4-byte tip patches) and filters
  through a ``FilterIndex`` (chain/filters.py), so a replica's steady-
  state QPS is dict lookups and byte splices, measured in
  benchmarks/query_plane.py.

- **Governor admission.**  Every session gets a per-peer query budget
  (node/governor.py ``ResourceGovernor``) charged at the dispatch
  door, same classes and same economics as the full node — a replica
  is cheap, not free.

``p1 serve --workers N`` runs N such processes against one store on one
port via ``SO_REUSEPORT``, so host query throughput scales with cores
while the consensus node only mines and validates.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import mmap
import os
import secrets
import struct
import time
from pathlib import Path

from p1_tpu.chain.filters import FilterHeaderChain, FilterIndex
from p1_tpu.chain.proof import ProofCache, build_block_proofs
from p1_tpu.chain.store import MAGIC, V2_MAGIC, ChainStore
from p1_tpu.core.block import Block
from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.hashutil import sha256d
from p1_tpu.core.header import HEADER_SIZE
from p1_tpu.node import protocol
from p1_tpu.node.governor import (
    CLASS_QUERIES,
    WRITE_QUEUE_MAX,
    ResourceGovernor,
)
from p1_tpu.node.protocol import Hello, MsgType
from p1_tpu.node.subscriptions import SubscriptionManager, block_items_index

log = logging.getLogger("p1_tpu.queryplane")

_LEN = struct.Struct(">I")
_CRC_SIZE = 4

#: Serving caps, mirroring the node's (one query must not pin the loop).
HEADERS_BATCH = 2000
FILTER_BATCH = 1000
SYNC_BATCH = 500
SYNC_BYTES = 8 << 20

#: A replica holds no per-peer consensus state, so it can afford far
#: more concurrent sessions than a node's MAX_PEERS — this is the knob
#: that lets thousands of light clients fan out across a few workers.
MAX_SESSIONS = 2048

#: How long a session may sit silent before the replica closes it.
#: No PING probing here — reconnecting to a replica is cheap, and the
#: simple read deadline keeps dead sockets from pinning session slots.
IDLE_TIMEOUT_S = 120.0


class _Entry:
    """One indexed record: everything fork choice and serving need,
    without retaining a single parsed object."""

    __slots__ = ("height", "work", "prev", "off", "length")

    def __init__(self, height: int, work: int, prev: bytes, off: int, length: int):
        self.height = height
        self.work = work
        self.prev = prev
        #: Packed payload location: ``(source index << _SRC_SHIFT) |
        #: byte offset`` into that source's mmap — source 0 is the
        #: whole file for a single-file store, one source per segment
        #: for a segmented one.  0 = genesis (no record anywhere).
        self.off = off
        self.length = length


#: Packed-offset split for ``_Entry.off``: low bits are the byte offset
#: inside one mapped source (44 bits ≫ any segment bound), high bits the
#: source index.
_SRC_SHIFT = 44
_SRC_MASK = (1 << _SRC_SHIFT) - 1


class _SegSrc:
    """One memory-mapped record source (the single file, or one
    segment): its scan cursor plus the inode pin that detects
    heal/compaction rewrites underneath us."""

    __slots__ = ("name", "fd", "mm", "mapped", "ino", "off")

    def __init__(self, name: str, fd: int):
        self.name = name
        self.fd = fd
        self.mm: mmap.mmap | None = None
        self.mapped = 0
        self.ino = os.fstat(fd).st_ino
        self.off = 0  # next unscanned byte

    def close(self) -> None:
        if self.mm is not None:
            self.mm.close()
            self.mm = None
        if self.fd is not None:
            os.close(self.fd)
            self.fd = None


class ReplicaView:
    """A flock-free, incrementally refreshed read view of a chain store.

    Correctness model: the store is the node's own append-only log of
    blocks it fully validated before persisting, protected per record by
    the v3 CRC (chain/store.py) — the replica therefore TRUSTS record
    contents the same way the node's own ``trusted=True`` resume does,
    and spends its cycles on indexing, not revalidation.  Clients
    verify what they receive anyway (headers by PoW replay, proofs by
    merkle recombination — the protocol is evidence-based end to end).
    """

    def __init__(self, path: str | os.PathLike, difficulty: int, retarget=None):
        self.path = Path(path)
        self.difficulty = difficulty
        self.retarget = retarget
        self.genesis = make_genesis(difficulty, retarget)
        self.proof_cache = ProofCache()
        self.filter_index = FilterIndex()
        #: The replica's own filter-header commitment chain, rebuilt
        #: from record bytes at attach and advanced per refresh.  It is
        #: DERIVED, not copied: filters are pure functions of block
        #: bytes, so this replica's chain matches the writer's — and a
        #: wallet cross-checking two replicas compares commitments
        #: neither could forge independently.
        self.filter_headers = FilterHeaderChain()
        #: Mapped record sources, in record order: [whole file] for the
        #: single-file layout, one per segment (manifest order) for a
        #: segmented store — ``_Entry.off`` packs the source index.
        self._srcs: list[_SegSrc] = []
        self._by_name: dict[str, _SegSrc] = {}
        self._segmented = False
        self._manifest_key: tuple | None = None
        self._manifest_rows: list = []
        self.records = 0
        self.rescans = 0  # full rescans (inode change / truncation)
        self.refreshes = 0
        self._entries: dict[bytes, _Entry] = {}
        self._pending: dict[bytes, list[tuple[bytes, bytes, int, int]]] = {}
        self._tx_index: dict[bytes, bytes | list[bytes]] = {}
        self._main: list[bytes] = []
        self._tip: bytes = b""
        #: Snapshot-bootstrap base (node/provision.py): when a
        #: ``.bootbase`` sidecar sits next to the store, heights
        #: ``1..assumed_base`` are ADOPTED — PoW-verified headers and
        #: peer-served filter headers without bodies on disk (the
        #: snapshot carries the state, not the history).  Queries below
        #: the base refuse bodies/filters honestly, exactly like a
        #: pruned archive; 0 = ordinary full store.
        self.assumed_base = 0
        self._boot_headers: list[bytes] = []  # heights 1..base
        self._boot_fheaders: list[bytes] = []  # heights 0..base
        self._load_bootbase()
        self._reset_index()
        self.refresh()

    # -- attach / rescan ---------------------------------------------------

    def _load_bootbase(self) -> None:
        """Read the ``.bootbase`` sidecar (if any) a snapshot bootstrap
        left next to the store, and verify its adopted header prefix
        actually links from OUR genesis — a sidecar written against a
        different chain (or torn mid-write, which read_bootbase already
        rejects) must fail the attach, not serve a phantom history."""
        from p1_tpu.node.provision import read_bootbase

        bb = read_bootbase(self.path)
        if bb is None:
            return
        base, headers, fheaders = bb
        prev = self.genesis.block_hash()
        for hdr in headers:
            if hdr[4:36] != prev:
                raise ValueError(
                    f"{self.path}: bootbase sidecar does not link from"
                    " this chain's genesis"
                )
            prev = sha256d(hdr)
        self.assumed_base = base
        self._boot_headers = headers
        self._boot_fheaders = fheaders

    def _reset_index(self) -> None:
        ghash = self.genesis.block_hash()
        self._entries = {
            ghash: _Entry(0, 1 << self.difficulty, b"", 0, 0)
        }
        self._pending = {}
        self._tx_index = {
            tx.txid(): ghash for tx in self.genesis.txs
        }
        self._main = [ghash]
        self._tip = ghash
        self.records = 0
        if self.assumed_base:
            self._seed_bootbase()

    def _seed_bootbase(self) -> None:
        """Seed the adopted prefix (heights ``1..assumed_base``) into a
        fresh index — called from every ``_reset_index`` so full rescans
        (inode replaced, layout change) re-adopt the base before the
        store's body records (all above the base) re-connect to it.
        Adopted entries carry ``off=0`` with height > 0: the existing
        raw_record contract already reads that as "no bytes anywhere",
        which IS the honest body refusal below the base."""
        work = self._entries[self._tip].work
        prev_hash = self._tip
        for h, hdr in enumerate(self._boot_headers, start=1):
            bhash = sha256d(hdr)
            work += 1 << _header_difficulty(hdr)
            self._entries[bhash] = _Entry(h, work, hdr[4:36], 0, 0)
            self._main.append(bhash)
            prev_hash = bhash
        self._tip = prev_hash
        # Adopt the peer-served commitment prefix wholesale: filters
        # below the base cannot be recomputed (no bodies), and sync()
        # extends above it from real record bytes.  Only when shorter —
        # a live rescan must not wipe commitments already derived.
        if len(self.filter_headers) <= self.assumed_base:
            self.filter_headers.seed(
                list(
                    zip(
                        self._main[: self.assumed_base + 1],
                        self._boot_fheaders,
                    )
                )
            )

    def close(self) -> None:
        for src in self._srcs:
            src.close()
        self._srcs = []
        self._by_name = {}
        self._manifest_key = None
        self._manifest_rows = []

    def _full_reset(self) -> None:
        """Void every cached offset and start over (inode replaced,
        file truncated, layout changed).  Caches keyed by block hash
        (proofs, filters) stay valid: a hash names the same bytes in
        any inode."""
        self.close()
        self._reset_index()
        self.rescans += 1

    def _slice(self, packed_off: int, length: int) -> bytes:
        src = self._srcs[packed_off >> _SRC_SHIFT]
        off = packed_off & _SRC_MASK
        return bytes(src.mm[off : off + length])

    def refresh(self) -> int:
        """Bring the view up to date with the store; returns how many
        new records were indexed.  NEVER takes any lock — reading races
        the writer only at the torn tail, which the per-record CRC
        resolves (an incomplete record fails its checksum and is
        retried on the next refresh, after the writer's flush
        completes).  Segmented stores re-read the manifest when its
        inode moves (every roll rewrites it) and keep per-segment scan
        cursors: sealed history is scanned once, only the ACTIVE
        segment is re-walked — and a single-file store upgrading to
        segments under a live attach is detected as a layout change
        and triggers one clean rescan."""
        try:
            head = b""
            with open(self.path, "rb") as f:
                head = f.read(len(MAGIC))
        except FileNotFoundError:
            # Store not created yet (node about to boot): empty view.
            self.close()
            self._reset_index()
            return 0
        from p1_tpu.chain.segstore import SEG_MAGIC

        segmented = head == SEG_MAGIC
        if self._srcs and segmented != self._segmented:
            self._full_reset()  # live upgrade: single file became a manifest
        self._segmented = segmented
        old_tip = self._tip
        new = (
            self._refresh_segmented()
            if segmented
            else self._refresh_single(head)
        )
        if new is None:  # a source was replaced underneath us: rescan
            self._full_reset()
            new = (
                self._refresh_segmented()
                if segmented
                else self._refresh_single(head)
            )
            new = new or 0
        if new:
            self.records += new
            if (
                self._tip != old_tip
                or len(self._main) - 1 != self._entries[self._tip].height
            ):
                self._rebuild_main()
            self.filter_headers.sync(
                self.tip_height, self.hash_at, self.filter_at
            )
        self.refreshes += 1
        return new

    def _open_src(self, name: str, path) -> _SegSrc | None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            return None
        src = _SegSrc(name, fd)
        self._srcs.append(src)
        self._by_name[name] = src
        return src

    def _scan_src(self, src: _SegSrc, path) -> int | None:
        """Advance one source's scan cursor; returns records indexed,
        or None when the file was replaced/truncated underneath us
        (caller does a full rescan)."""
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return None
        if st.st_ino != src.ino or st.st_size < src.mapped:
            return None
        size = os.fstat(src.fd).st_size
        if size < len(MAGIC):
            return 0
        if size > src.mapped:
            if src.mm is not None:
                src.mm.close()
            src.mm = mmap.mmap(src.fd, size, prot=mmap.PROT_READ)
            src.mapped = size
        mm = src.mm
        if src.off == 0:
            head = bytes(mm[: len(MAGIC)])
            if head == V2_MAGIC:
                raise ValueError(
                    f"{self.path}: v2 chain store — upgrade with `p1 fsck`"
                    " or `p1 compact` before serving replicas"
                )
            if head != MAGIC:
                return 0  # torn first write mid-roll: retry next refresh
            src.off = len(MAGIC)
        src_idx = self._srcs.index(src)
        new = 0
        while src.off < src.mapped:
            end = ChainStore._v3_record_at(mm, src.off)
            if end is None:
                # Torn tail (writer mid-append) or trailing damage the
                # writer will heal: stop here, retry next refresh.
                break
            p_off = src.off + _LEN.size
            p_len = end - p_off - _CRC_SIZE
            self._index_record(
                src_idx, mm, (src_idx << _SRC_SHIFT) | p_off, p_off, p_len
            )
            src.off = end
            new += 1
        return new

    def _refresh_single(self, head: bytes) -> int | None:
        if not self._srcs:
            if head and head != MAGIC and head != V2_MAGIC and len(head) >= len(MAGIC):
                raise ValueError(f"{self.path}: not a chain store")
            if self._open_src("", self.path) is None:
                return 0
        return self._scan_src(self._srcs[0], self.path)

    def _refresh_segmented(self) -> int | None:
        from p1_tpu.chain.segstore import SegmentInfo, read_manifest

        try:
            mst = os.stat(self.path)
        except FileNotFoundError:
            return None
        key = (mst.st_ino, mst.st_size, mst.st_mtime_ns)
        if key != self._manifest_key:
            manifest = read_manifest(self.path)
            if manifest is None:
                return 0  # mid-replace race: retry next refresh
            self._manifest_rows = [
                SegmentInfo.from_json(r) for r in manifest.get("segments", [])
            ]
            self._manifest_key = key
        seg_dir = self.path.with_name(self.path.name + ".d")
        total = 0
        for row in self._manifest_rows:
            if row.pruned:
                raise ValueError(
                    f"{self.path}: pruned store cannot back a replica — "
                    "deep bodies are gone; serve from an archive copy"
                )
            src = self._by_name.get(row.name)
            path = seg_dir / row.name
            if src is None:
                src = self._open_src(row.name, path)
                if src is None:
                    break  # manifest ahead of the directory: retry later
            n = self._scan_src(src, path)
            if n is None:
                return None  # heal/compaction replaced this segment
            total += n
        return total

    def _index_record(
        self, src_idx: int, mm, packed_off: int, off: int, length: int
    ) -> None:
        """Index one checksum-valid record at payload ``off`` in
        ``mm``: header digest, fork choice, txid index — no object
        construction.  ``packed_off`` is what the entry retains."""
        hdr = bytes(mm[off : off + HEADER_SIZE])
        if len(hdr) < HEADER_SIZE:
            return
        bhash = sha256d(hdr)
        if bhash in self._entries:
            return  # duplicate record (e.g. a snapshot's genesis row)
        prev = hdr[4:36]  # BlockHeader layout: u32 version + 32s prev_hash
        parent = self._entries.get(prev)
        if parent is None:
            # Out-of-line record (shouldn't happen in a node's log, which
            # appends in connect order — but a foreign/hand-built store
            # may interleave): park until the parent shows up.
            self._pending.setdefault(prev, []).append(
                (bhash, hdr, packed_off, length)
            )
            return
        self._connect(bhash, hdr, packed_off, length, parent)
        # Drain anything that was waiting on this block, recursively.
        queue = [bhash]
        while queue:
            for child, chdr, coff, clen in self._pending.pop(queue.pop(), []):
                self._connect(
                    child, chdr, coff, clen, self._entries[chdr[4:36]]
                )
                queue.append(child)

    def _connect(self, bhash, hdr, off, length, parent) -> None:
        diff = _header_difficulty(hdr)
        entry = _Entry(
            parent.height + 1, parent.work + (1 << diff), hdr[4:36], off, length
        )
        self._entries[bhash] = entry
        tip = self._entries[self._tip]
        if entry.work > tip.work or (
            entry.work == tip.work and bhash < self._tip
        ):
            self._tip = bhash
        self._index_txids(bhash, off, length)

    def _index_txids(self, bhash: bytes, packed_off: int, length: int) -> None:
        """txid -> block hash entries for one record, hashing raw tx
        slices straight off the map (no Transaction objects)."""
        mm = self._srcs[packed_off >> _SRC_SHIFT].mm
        off = packed_off & _SRC_MASK
        end = off + length
        pos = off + HEADER_SIZE
        if pos + 4 > end:
            return
        (ntx,) = _LEN.unpack_from(mm, pos)
        pos += 4
        for _ in range(ntx):
            if pos + 4 > end:
                return  # malformed (CRC-valid but not a block): serve raw only
            (tlen,) = _LEN.unpack_from(mm, pos)
            pos += 4
            if pos + tlen > end:
                return
            txid = sha256d(bytes(mm[pos : pos + tlen]))
            pos += tlen
            have = self._tx_index.get(txid)
            if have is None:
                self._tx_index[txid] = bhash
            elif isinstance(have, bytes):
                if have != bhash:
                    self._tx_index[txid] = [have, bhash]
            elif bhash not in have:
                have.append(bhash)

    def _rebuild_main(self) -> None:
        """Re-derive the height -> hash list for the current tip.  Walks
        back only until it meets the old main chain (O(new blocks + fork
        depth)), the incremental trick Chain's reorg paths use."""
        suffix: list[bytes] = []
        h = self._tip
        while True:
            entry = self._entries[h]
            if (
                entry.height < len(self._main)
                and self._main[entry.height] == h
            ):
                break
            suffix.append(h)
            if entry.height == 0:
                break
            h = entry.prev
        keep = self._entries[suffix[-1]].height if suffix else len(self._main)
        del self._main[keep:]
        self._main.extend(reversed(suffix))

    # -- queries -----------------------------------------------------------

    @property
    def tip_height(self) -> int:
        return len(self._main) - 1

    def _is_main(self, bhash: bytes) -> bool:
        entry = self._entries.get(bhash)
        return (
            entry is not None
            and entry.height < len(self._main)
            and self._main[entry.height] == bhash
        )

    def raw_record(self, bhash: bytes) -> bytes | None:
        entry = self._entries.get(bhash)
        if entry is None or entry.off == 0:
            if entry is not None and entry.height == 0:
                return self.genesis.serialize()
            return None
        return self._slice(entry.off, entry.length)

    def read_block(self, bhash: bytes) -> Block | None:
        raw = self.raw_record(bhash)
        if raw is None:
            return None
        return Block.deserialize(raw)

    def raw_header(self, height: int) -> bytes | None:
        if not 0 <= height < len(self._main):
            return None
        entry = self._entries[self._main[height]]
        if entry.off == 0:
            if height == 0:
                return self.genesis.header.serialize()
            if height <= self.assumed_base:
                # Adopted bootbase header: on main at height > 0 with no
                # record bytes, the only entries with off 0 are the
                # seeded prefix — serve the header the bootstrap
                # PoW-verified (a bootstrapped replica can feed another
                # replica's header sync).
                return self._boot_headers[height - 1]
            return None
        return self._slice(entry.off, HEADER_SIZE)

    def _start_after(self, locator: list[bytes]) -> int:
        for h in locator:
            entry = self._entries.get(h)
            if entry is not None and self._is_main(h):
                return entry.height + 1
        return 0

    def headers_after(self, locator: list[bytes], limit: int = HEADERS_BATCH):
        start = self._start_after(locator)
        end = min(start + limit, len(self._main))
        return [self.raw_header(h) for h in range(start, end)]

    def blocks_after(
        self,
        locator: list[bytes],
        limit: int = SYNC_BATCH,
        max_bytes: int = SYNC_BYTES,
    ):
        start = self._start_after(locator)
        end = min(start + limit, len(self._main))
        out, total = [], 0
        for h in range(start, end):
            raw = self.raw_record(self._main[h])
            if raw is None:
                # Adopted bootbase height: the body was never on this
                # disk.  Stop — a short (or empty) reply is the same
                # honest refusal a pruned archive gives.
                break
            total += len(raw) + 4
            if out and total > max_bytes:
                break
            out.append(raw)
        return out

    def filters_range(self, start: int, count: int):
        """(block hash, filter) pairs for main heights [start, start+count)."""
        out = []
        for h in range(start, min(start + count, len(self._main))):
            if 0 < h <= self.assumed_base:
                break  # bodyless adopted height: refuse, never guess
            bhash = self._main[h]
            fbytes = self.filter_index.get_or_build(
                bhash, lambda bh: self.read_block(bh)
            )
            out.append((bhash, fbytes))
        return out

    # -- subscription source (node/subscriptions.py duck type) -------------

    def hash_at(self, height: int) -> bytes | None:
        if 0 <= height < len(self._main):
            return self._main[height]
        return None

    def raw_header_at(self, height: int) -> bytes | None:
        return self.raw_header(height)

    def filter_at(self, height: int) -> bytes | None:
        if 0 < height <= self.assumed_base:
            return None  # bodyless adopted height (bootbase)
        bhash = self.hash_at(height)
        if bhash is None:
            return None
        return self.filter_index.get_or_build(
            bhash, lambda bh: self.read_block(bh)
        )

    def fheader_at(self, height: int) -> bytes | None:
        return self.filter_headers.header_at(height)

    def block_items_at(self, height: int):
        bhash = self.hash_at(height)
        if bhash is None:
            return None
        block = self.read_block(bhash)
        if block is None:
            return None
        return block_items_index(block)

    def proof_payload(self, txid: bytes) -> bytes:
        """The wire PROOF reply for ``txid`` at this view's tip — same
        cache economics as the node's ``_proof_payload``."""
        have = self._tx_index.get(txid)
        if have is None:
            return protocol.encode_proof(None)
        candidates = [have] if isinstance(have, bytes) else have
        bhash = next((b for b in candidates if self._is_main(b)), None)
        if bhash is None:
            return protocol.encode_proof(None)
        entry = self.proof_cache.get(bhash, txid)
        if entry is None:
            block = self.read_block(bhash)
            if block is None:
                return protocol.encode_proof(None)
            height = self._entries[bhash].height
            txids = [tx.txid() for tx in block.txs]
            for tid, proof in build_block_proofs(block, height, txids).items():
                e = self.proof_cache.add(bhash, tid, proof)
                if tid == txid:
                    entry = e
        if entry.payload is None:
            self.proof_cache.note_payload(
                entry, protocol.encode_proof(entry.proof)
            )
        return protocol.patch_proof_tip(entry.payload, self.tip_height)


def _header_difficulty(hdr: bytes) -> int:
    """The u32 difficulty field straight out of an 80-byte header record
    (core/header.py ``>I32s32sIII``: bytes 72..76) — the one header
    field fork choice needs per record, read without an object parse."""
    return struct.unpack_from(">I", hdr, 72)[0]


class QueryPlaneServer:
    """One replica worker: an asyncio server speaking the READ subset of
    the wire protocol over a ``ReplicaView``, behind governor admission.

    Served: HELLO, GETHEADERS, GETFILTERS, GETPROOF, GETBLOCKS,
    GETSTATUS, PING.  Everything write-shaped (BLOCK/TX pushes) or
    ledger-shaped (GETACCOUNT, GETFEES, GETMEMPOOL — they need tip
    state only the consensus node holds) is ignored; a client that
    needs those talks to the node.
    """

    def __init__(
        self,
        view: ReplicaView,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        refresh_interval_s: float = 0.25,
        max_sessions: int = MAX_SESSIONS,
        idle_timeout_s: float = IDLE_TIMEOUT_S,
        reuse_port: bool = False,
        governor: ResourceGovernor | None = None,
    ):
        self.view = view
        self.host = host
        self._want_port = port
        self.port: int | None = None
        self.refresh_interval_s = refresh_interval_s
        self.max_sessions = max_sessions
        self.idle_timeout_s = idle_timeout_s
        self.reuse_port = reuse_port
        self.governor = governor or ResourceGovernor()
        # Telemetry registry (node/telemetry.py): replica-side query
        # latency + the counters below, served over GETMETRICS exactly
        # like the consensus node's.  Host clock by design — the
        # replica is a real-socket separate-process tier the simulator
        # never runs.
        from p1_tpu.node.telemetry import MetricsRegistry

        self.telemetry = MetricsRegistry()
        #: The wallet push plane (node/subscriptions.py): watch-filter
        #: subscriptions notified from the refresh loop at each new
        #: record batch, degrading slow consumers down the
        #: coalesce → drop-to-cursor → disconnect ladder.
        self.subscriptions = SubscriptionManager(
            view, registry=self.telemetry
        )
        self.instance_nonce = secrets.randbits(64) | 1
        self._server: asyncio.Server | None = None
        self._sessions: set[asyncio.Task] = set()
        self._refresh_task: asyncio.Task | None = None
        self._running = False
        self.started_at = time.monotonic()
        self.queries_served = collections.Counter()
        self.admission_dropped = 0
        self.sessions_refused = 0
        self.sessions_total = 0
        #: Sessions disconnected at the hard write-queue cap — the same
        #: squat guard node sessions have: a subscriber (or a client
        #: that keeps asking without reading) cannot pin replica memory.
        self.sessions_dropped_squat = 0
        #: Rolling per-second query counts for the QPS figure (last 60 s).
        self._qps_window: collections.deque[tuple[int, int]] = (
            collections.deque(maxlen=60)
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self.started_at = time.monotonic()
        self._server = await asyncio.start_server(
            self._on_client,
            self.host,
            self._want_port,
            reuse_port=self.reuse_port or None,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._refresh_task = asyncio.create_task(self._refresh_loop())
        self._refresh_task.add_done_callback(self._refresh_done)
        log.info(
            "replica serving %s on %s:%d (tip height %d)",
            self.view.path,
            self.host,
            self.port,
            self.view.tip_height,
        )

    async def drain(self) -> int:
        """Graceful replica drain (`p1 serve` on SIGTERM): stop
        accepting new sessions FIRST, push a final EVENTGAP resume
        cursor to every live subscriber so wallets fail over instantly
        instead of waiting out a dead socket, then stop.  Returns how
        many subscribers were drained."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        drained = await self.subscriptions.drain()
        await self.stop()
        return drained

    async def stop(self) -> None:
        self._running = False
        self.subscriptions.close_all()
        if self._refresh_task is not None:
            self._refresh_task.cancel()
            await asyncio.gather(self._refresh_task, return_exceptions=True)
            self._refresh_task = None
        for task in list(self._sessions):
            task.cancel()
        await asyncio.gather(*self._sessions, return_exceptions=True)
        self._sessions.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.view.close()

    def _refresh_done(self, task: asyncio.Task) -> None:
        """A refresh loop that dies of an unexpected exception (the
        per-iteration handler only expects OSError/ValueError) would
        strand the replica serving an ever-staler tip with no sign of
        trouble — same lost-task shape as the node's round-3 dead
        store-recovery loop, same cure: observe the wreck, log it, and
        respawn while still running (the loop's leading sleep keeps a
        persistent crash from spinning)."""
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        log.error("replica refresh loop died: %r — respawning", exc)
        if self._running:
            self._refresh_task = asyncio.create_task(self._refresh_loop())
            self._refresh_task.add_done_callback(self._refresh_done)

    async def _refresh_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.refresh_interval_s)
            try:
                if self.view.refresh():
                    await self.subscriptions.notify()
            except (OSError, ValueError) as e:
                # A transient read fault or a mid-run store replacement
                # with something unreadable: keep serving the view we
                # hold and keep retrying — a replica that dies of one
                # bad stat() defeats its purpose.
                log.warning("replica refresh failed: %s", e)

    # -- sessions ----------------------------------------------------------

    def _count_query(self, mtype) -> None:
        self.queries_served[mtype.name] += 1
        now = int(time.monotonic())
        if self._qps_window and self._qps_window[-1][0] == now:
            sec, n = self._qps_window[-1]
            self._qps_window[-1] = (sec, n + 1)
        else:
            self._qps_window.append((now, 1))

    def qps(self) -> float:
        """Queries/s over the rolling window (excludes the current
        second only if it is the lone sample)."""
        if not self._qps_window:
            return 0.0
        span = max(1, self._qps_window[-1][0] - self._qps_window[0][0] + 1)
        return sum(n for _, n in self._qps_window) / span

    def status(self) -> dict:
        v = self.view
        return {
            "role": "replica",
            "store": str(v.path),
            "height": v.tip_height,
            "tip": v._main[-1].hex() if v._main else "",
            "records": v.records,
            "refreshes": v.refreshes,
            "rescans": v.rescans,
            "assumed_base": v.assumed_base,
            "sessions": len(self._sessions),
            "sessions_total": self.sessions_total,
            "sessions_refused": self.sessions_refused,
            "sessions_dropped_squat": self.sessions_dropped_squat,
            "filter_headers": len(self.view.filter_headers),
            "subscriptions": self.subscriptions.snapshot(),
            "queries": {
                "served": dict(self.queries_served),
                "total": sum(self.queries_served.values()),
                "qps": round(self.qps(), 1),
                "admission_dropped": self.admission_dropped,
                "proof_cache": v.proof_cache.snapshot(),
                "filter_cache": v.filter_index.snapshot(),
            },
        }

    def _hello(self) -> bytes:
        return protocol.encode_hello(
            Hello(
                self.view.genesis.block_hash(),
                self.view.tip_height,
                self.port or 0,
                self.instance_nonce,
            )
        )

    async def _on_client(self, reader, writer) -> None:
        if len(self._sessions) >= self.max_sessions:
            self.sessions_refused += 1
            writer.close()
            return
        task = asyncio.current_task()
        self._sessions.add(task)
        self.sessions_total += 1
        sid = self.sessions_total
        subscribed = False
        budget = self.governor.budget()

        async def push(payload: bytes) -> None:
            # Pushes never drain: the transport buffer is the bounded
            # subscription queue, read back by the ladder below.
            protocol.write_frame_nowait(writer, payload)

        def buffer_size() -> int:
            transport = writer.transport
            return (
                transport.get_write_buffer_size()
                if transport is not None
                else 0
            )

        try:
            await protocol.write_frame(writer, self._hello())
            payload = await asyncio.wait_for(
                protocol.read_frame(reader), timeout=10.0
            )
            mtype, hello = protocol.decode(payload)
            if mtype is not MsgType.HELLO:
                raise protocol.ProtocolError("expected HELLO")
            if hello.genesis_hash != self.view.genesis.block_hash():
                raise protocol.ChainMismatch("genesis mismatch")
            while self._running:
                # A subscribed session is legitimately silent for as
                # long as blocks are quiet — the idle deadline applies
                # only to the request/reply shape.
                payload = await asyncio.wait_for(
                    protocol.read_frame(reader),
                    timeout=None if subscribed else self.idle_timeout_s,
                )
                mtype, body = protocol.decode(payload)
                if mtype in _QUERY_TYPES and not self.governor.admit(
                    budget, CLASS_QUERIES
                ):
                    self.admission_dropped += 1
                    continue
                if mtype is MsgType.SUBSCRIBE:
                    cursor, items = body
                    self._count_query(mtype)
                    ok = await self.subscriptions.subscribe(
                        sid,
                        items,
                        cursor,
                        send=push,
                        buffer_size=buffer_size,
                        close=writer.close,
                    )
                    if not ok:
                        # Unverifiable resume cursor (pruned window or a
                        # wallet that last spoke to a liar): refusing by
                        # disconnect is the failover signal.
                        raise protocol.ProtocolError(
                            "resume cursor not on the committed chain"
                        )
                    subscribed = True
                    continue
                if mtype is MsgType.UNSUBSCRIBE:
                    self._count_query(mtype)
                    self.subscriptions.unsubscribe(sid)
                    subscribed = False
                    continue
                with self.telemetry.span("query.request_s"):
                    reply = self._answer(mtype, body)
                    if reply is not None:
                        self._count_query(mtype)
                        if buffer_size() > WRITE_QUEUE_MAX:
                            # Asking while never reading: same hard-cap
                            # disconnect as a squatting node peer.
                            self.sessions_dropped_squat += 1
                            break
                        await protocol.write_frame(writer, reply)
        except (
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            TimeoutError,
            ConnectionError,
            ValueError,
            OSError,
        ):
            pass  # replica sessions end quietly; clients just reconnect
        finally:
            self.subscriptions.drop(sid)
            self._sessions.discard(task)
            writer.close()

    def _answer(self, mtype, body) -> bytes | None:
        v = self.view
        if mtype is MsgType.GETHEADERS:
            return protocol.encode_headers_raw(
                v.headers_after(body, HEADERS_BATCH)
            )
        if mtype is MsgType.GETFILTERS:
            start, count = body
            entries = v.filters_range(start, min(count, FILTER_BATCH))
            return protocol.encode_filters(start, entries)
        if mtype is MsgType.GETPROOF:
            return v.proof_payload(body)
        if mtype is MsgType.GETBLOCKS:
            return protocol.encode_blocks_raw(
                v.blocks_after(body, SYNC_BATCH, SYNC_BYTES)
            )
        if mtype is MsgType.GETFILTERHEADERS:
            start, count = body
            return protocol.encode_filterheaders(
                start,
                v.filter_headers.range(start, min(count, FILTER_BATCH)),
            )
        if mtype is MsgType.GETSTATUS:
            return protocol.encode_status(self.status())
        if mtype is MsgType.GETMETRICS:
            # The replica serves its own registry — a fleet scrape sees
            # every worker's latency surface, not just the writer's.
            return protocol.encode_metrics(
                {
                    "role": "replica",
                    "height": v.tip_height,
                    "queries_total": sum(self.queries_served.values()),
                    **self.telemetry.snapshot(),
                }
            )
        if mtype is MsgType.PING:
            return protocol.encode_pong(body)
        return None  # pushes / ledger queries: not this plane's job


_QUERY_TYPES = frozenset(
    {
        MsgType.GETHEADERS,
        MsgType.GETFILTERS,
        MsgType.GETFILTERHEADERS,
        MsgType.GETPROOF,
        MsgType.GETBLOCKS,
        MsgType.GETSTATUS,
        MsgType.GETMETRICS,
        MsgType.SUBSCRIBE,
        MsgType.UNSUBSCRIBE,
    }
)


async def serve_replica(
    store_path,
    difficulty: int,
    *,
    retarget=None,
    host: str = "127.0.0.1",
    port: int = 0,
    refresh_interval_s: float = 0.25,
    reuse_port: bool = False,
) -> QueryPlaneServer:
    """Attach a ``ReplicaView`` and start one worker (the `p1 serve`
    core, also what tests drive directly)."""
    view = ReplicaView(store_path, difficulty, retarget)
    server = QueryPlaneServer(
        view,
        host=host,
        port=port,
        refresh_interval_s=refresh_interval_s,
        reuse_port=reuse_port,
    )
    await server.start()
    return server
