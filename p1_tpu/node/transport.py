"""The transport seam: how a node touches the network and the clock.

The thousand-node wall (ROADMAP item 4): every harness this repo ever
built — the `p1 net` subprocess mesh, the byzantine soak, HostilePeer /
GreedyPeer — drives REAL sockets through the one shared kernel and the
one shared wall clock, which tops out around seven heavily-loaded nodes
on the 1-vCPU host and couples every liveness/stall deadline to host
scheduling noise (the round-6..9 deflaking ledger is the evidence).
Bitcoin-Core-lineage systems validate emergent consensus behavior
(partition heal, eclipse resistance, churn) on *simulated* meshes; the
missing primitive here was a seam between the node and its network.

This module is that seam, deliberately small:

- ``Clock`` — ``monotonic()`` (deadlines, rate limits) and ``wall()``
  (block timestamps, propagation telemetry).  ``SystemClock`` is
  ``time.monotonic``/``time.time``; the simulator's ``VirtualClock``
  (node/netsim.py) is a number the event loop advances.  Everything in
  the node that used to read ``time.*`` directly now reads its
  transport's clock — enforced by the wall-clock lint
  (tests/test_simlint.py), so future code stays sim-compatible.
- ``Listener`` — the slice of ``asyncio.Server`` the node actually
  uses: the bound port, ``close()``, ``wait_closed()``.
- ``Transport`` — ``listen()`` + ``connect()`` yielding the standard
  ``(StreamReader, StreamWriter)`` pair.  ``SocketTransport`` delegates
  straight to asyncio (byte-for-byte the historical behavior — the
  whole pre-existing socket suite runs through it unchanged);
  ``SimTransport`` (node/netsim.py) delivers frames through in-memory
  links with latency/jitter/bandwidth models under virtual time.

Sleeps and ``asyncio.wait_for`` deadlines deliberately do NOT go
through the seam: they are already loop-relative (``loop.time()``), and
the simulator virtualizes the loop itself (netsim.SimLoop), so an
``asyncio.sleep(30)`` inside a simulated node costs microseconds of
wall time.  Only *direct* ``time.*`` reads bypass the loop — those are
what the seam (and the lint) exist to catch.
"""

from __future__ import annotations

import asyncio
import time

__all__ = ["Clock", "SystemClock", "Listener", "SocketListener", "Transport", "SocketTransport"]


class Clock:
    """Time source interface: monotonic seconds for deadlines/rates,
    wall seconds for timestamps that cross process boundaries."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def wall(self) -> float:
        raise NotImplementedError


class SystemClock(Clock):
    """The host's clocks — the blessed home of ``time.monotonic`` /
    ``time.time`` for everything behind the transport seam."""

    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)


class Listener:
    """What the node needs from a listening endpoint."""

    @property
    def port(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    async def wait_closed(self) -> None:
        raise NotImplementedError


class SocketListener(Listener):
    """An ``asyncio.Server`` behind the ``Listener`` surface."""

    def __init__(self, server: asyncio.Server):
        self._server = server

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def close(self) -> None:
        self._server.close()

    async def wait_closed(self) -> None:
        await self._server.wait_closed()


class Transport:
    """How a node (or harness actor) reaches the network.  One instance
    per participant — the simulator binds a source address per handle so
    per-host accounting (bans, ADDR budgets) keeps working."""

    clock: Clock

    async def listen(self, on_conn, host: str, port: int) -> Listener:
        """Bind ``host:port`` (0 = ephemeral) and invoke ``on_conn(reader,
        writer)`` per inbound connection, asyncio.start_server-style."""
        raise NotImplementedError

    async def connect(
        self, host: str, port: int, local_addr: tuple[str, int] | None = None
    ):
        """Dial ``host:port``; returns ``(reader, writer)``.  ``local_addr``
        picks the source address (the loopback-alias trick the byzantine
        suite uses so bans land on the attacker's host)."""
        raise NotImplementedError


class SocketTransport(Transport):
    """The default: real sockets via asyncio, system clocks.  Stateless,
    so one shared instance serves every node in a process."""

    clock = SystemClock()

    async def listen(self, on_conn, host: str, port: int) -> Listener:
        return SocketListener(await asyncio.start_server(on_conn, host, port))

    async def connect(
        self, host: str, port: int, local_addr: tuple[str, int] | None = None
    ):
        return await asyncio.open_connection(host, port, local_addr=local_addr)


#: The process-wide default (stateless — see SocketTransport).
SOCKET_TRANSPORT = SocketTransport()
