"""Telemetry plane: counters, gauges, and bounded latency histograms.

The node's five hardened planes (governor, sim, chaos, snapshots, lint)
were observable only through flat counters and point-in-time ``status()``
dicts — no latency distributions, no per-stage timing, no export
surface.  This module is the measurement substrate the multi-core
pipeline split (ROADMAP item 2) and the wallet-plane SLOs (item 3) are
scoped against: Bitcoin Core's ``-debug=bench`` lineage (per-stage
block-connect timing) rebuilt on this repo's clock-seam discipline.

Design rules, in priority order:

- **Observers, not participants.**  Recording a metric must never
  change what the node does: no RNG, no set iteration, no feedback into
  any decision path.  The sim determinism pair (tests/test_telemetry.py)
  pins it — a 200-node scenario produces the SAME trace digest with
  telemetry enabled and disabled.
- **Clock-injectable.**  Every duration is read through the registry's
  injected clock (the node passes ``Node.clock.monotonic``), so the same
  instrumentation measures wall time on a live node and *virtual* time
  under ``SimLoop`` — and this module ships with ZERO wall-clock lint
  grants (tests/test_simlint.py pins that too).  The ``time.monotonic``
  spellings below are injectable *defaults*, never calls.
- **Bounded.**  Histograms are fixed-bucket (geometric, factor √2, one
  microsecond to ~two virtual minutes) plus a small ring buffer of
  recent raw samples; a long-lived node's telemetry memory is a
  constant.

Export surfaces: the ``GETMETRICS`` wire frame (protocol v12,
governor-admitted, SHED-droppable, served by `p1 serve` replicas too),
`p1 metrics` (human table / ``--json`` / ``--prom`` Prometheus text
exposition), and per-scenario telemetry sections in sim/chaos reports
(virtual-time propagation histograms scenarios assert p95 bounds on).
"""

from __future__ import annotations

import collections
import logging
import math
import time
from array import array as _array
from bisect import bisect_left as _bisect_left

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricsRegistry",
    "NodeLogAdapter",
    "format_prometheus",
    "format_table",
    "merge_histograms",
    "propagation_summary_ms",
]

#: Geometric bucket upper bounds for latency histograms, seconds: factor
#: √2 from 1 µs up to ~134 s (54 buckets).  Fixed and shared so any two
#: histograms merge bucket-for-bucket (the scenario reports merge one
#: per node), and so a percentile estimate is never more than one √2
#: step above the true sample (the property test's bound).
_BUCKET_FACTOR = math.sqrt(2.0)
LATENCY_BUCKETS: tuple[float, ...] = tuple(
    1e-6 * _BUCKET_FACTOR**i for i in range(54)
)

#: Raw recent samples kept per histogram (debugging/exactness window —
#: the percentile math runs on the buckets, which never forget).
RECENT_WINDOW = 256


class Counter:
    """A monotonic-by-convention named value.  Plain assignment is
    allowed (NodeMetrics' attribute compatibility needs ``+=``), so the
    registry never enforces monotonicity — it just holds the number."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A named point-in-time value (float)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value


class Histogram:
    """Fixed-bucket latency histogram with a bounded recent window.

    ``observe`` clamps at zero (a latency is never negative; a clock
    that steps backward under test must not corrupt the buckets) and is
    O(log buckets).  ``percentile`` returns the upper edge of the bucket
    holding the requested rank, clamped into ``[min, max]`` observed —
    an estimate that is always >= the true sample and at most one
    bucket factor above it (property-tested against a sorted-list
    oracle in tests/test_telemetry.py).
    """

    __slots__ = (
        "name",
        "bounds",
        "counts",
        "overflow",
        "count",
        "total",
        "vmin",
        "vmax",
        "recent",
        "_append_recent",
        "_nbuckets",
    )

    def __init__(self, name: str, bounds: tuple[float, ...] = LATENCY_BUCKETS):
        self.name = name
        self.bounds = bounds
        # An unboxed array, not a list of ints: observe() runs on the
        # node's per-frame hot path, and a boxed-int counts list costs
        # an int allocation per increment plus a cache line per touched
        # box (benchmarks/telemetry_overhead.py is the receipt).
        self.counts = _array("Q", [0]) * len(bounds)
        self.overflow = 0  # samples above the last bound
        self.count = 0
        self.total = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None
        self.recent: collections.deque = collections.deque(
            maxlen=RECENT_WINDOW
        )
        self._append_recent = self.recent.append
        self._nbuckets = len(bounds)

    def observe(self, value: float) -> None:
        v = value if value > 0.0 else 0.0
        i = _bisect_left(self.bounds, v)
        if i < self._nbuckets:
            self.counts[i] += 1
        else:
            self.overflow += 1
        self.count += 1
        self.total += v
        vmin = self.vmin
        if vmin is None or v < vmin:
            self.vmin = v
        vmax = self.vmax
        if vmax is None or v > vmax:
            self.vmax = v
        self._append_recent(v)

    def percentile(self, p: float) -> float | None:
        """Bucket-estimate of the ``p``-th percentile (0 < p <= 100),
        None when empty."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(self.count * p / 100.0))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                upper = self.bounds[i]
                break
        else:
            upper = self.vmax  # the rank lives in the overflow bucket
        est = min(upper, self.vmax)
        return max(est, self.vmin)

    def merge(self, other: "Histogram") -> None:
        """Fold ``other``'s buckets into this histogram (the scenario
        reports' cross-node aggregation).  Bucket layouts must match;
        the recent window is NOT merged (it is per-source by design)."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bucket layouts differ")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.overflow += other.overflow
        self.count += other.count
        self.total += other.total
        if other.vmin is not None and (
            self.vmin is None or other.vmin < self.vmin
        ):
            self.vmin = other.vmin
        if other.vmax is not None and (
            self.vmax is None or other.vmax > self.vmax
        ):
            self.vmax = other.vmax

    def summary(self) -> dict:
        """{count, sum, min, max, p50, p95, p99} — the JSON-ready shape."""
        if self.count == 0:
            return {
                "count": 0,
                "sum": 0.0,
                "min": None,
                "max": None,
                "p50": None,
                "p95": None,
                "p99": None,
            }
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin,
            "max": self.vmax,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }

    def snapshot(self) -> dict:
        """``summary()`` plus the sparse cumulative bucket table the
        Prometheus exposition needs: [[le, cumulative], ...] rows only
        where a bucket holds samples, plus the +Inf total."""
        out = self.summary()
        buckets = []
        cum = 0
        for le, c in zip(self.bounds, self.counts):
            if c:
                cum += c
                buckets.append([le, cum])
        buckets.append(["+Inf", self.count])
        out["buckets"] = buckets
        return out


def merge_histograms(hists) -> Histogram | None:
    """A fresh histogram holding the union of ``hists`` (None when the
    iterable is empty) — the cross-node aggregation primitive."""
    merged = None
    for h in hists:
        if merged is None:
            merged = Histogram(h.name, h.bounds)
        merged.merge(h)
    return merged


def propagation_summary_ms(
    registries, name: str = "block.propagation_s"
) -> dict | None:
    """Merge one named histogram across many registries and summarize in
    milliseconds — the sim/chaos reports' propagation section.  None
    when no registry holds samples (e.g. telemetry disabled)."""
    merged = merge_histograms(
        h
        for reg in registries
        for h in (reg.histograms.get(name),)
        if h is not None and h.count
    )
    if merged is None:
        return None
    return {
        "samples": merged.count,
        "p50_ms": round(1e3 * merged.percentile(50), 3),
        "p95_ms": round(1e3 * merged.percentile(95), 3),
        "p99_ms": round(1e3 * merged.percentile(99), 3),
        "max_ms": round(1e3 * merged.vmax, 3),
    }


class _Span:
    """One timed region: enter reads the clock, exit records the delta."""

    __slots__ = ("_hist", "_clock", "_t0")

    def __init__(self, hist: Histogram, clock):
        self._hist = hist
        self._clock = clock

    def __enter__(self):
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc):
        self._hist.observe(self._clock() - self._t0)
        return False


class _NullSpan:
    """The disabled-telemetry span: no clock read, no record."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class MetricsRegistry:
    """One process-visible metrics namespace: counters, gauges, and
    histograms in insertion order (deterministic rendering).

    ``enabled`` gates only the *latency* surface (``observe``/``span``):
    counters and gauges stay live regardless, because ``status()`` and
    the existing dashboards are built on them.  Disabling therefore
    removes every clock read telemetry would otherwise perform — the
    knob the determinism pair flips.
    """

    __slots__ = ("enabled", "counters", "gauges", "histograms", "_clock")

    def __init__(self, clock=time.monotonic, enabled: bool = True):
        self.enabled = enabled
        self._clock = clock
        self.counters: dict[str, Counter] = {}
        self.gauges: dict[str, Gauge] = {}
        self.histograms: dict[str, Histogram] = {}

    # -- construction (get-or-create, idempotent) -------------------------

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name)
        return g

    def histogram(
        self, name: str, bounds: tuple[float, ...] = LATENCY_BUCKETS
    ) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, bounds)
        return h

    # -- recording --------------------------------------------------------

    def now(self) -> float:
        """One injected-clock read (callers that time a region across
        early returns and cannot use ``span``)."""
        return self._clock()

    def observe(self, name: str, value: float) -> None:
        if self.enabled:
            self.histogram(name).observe(value)

    def span(self, name: str):
        """``with registry.span("stage.validate_s"): ...`` — times the
        region into the named histogram; a no-op (zero clock reads)
        when the registry is disabled.  Hot path: one dict get + one
        small allocation per call (a fresh _Span per region keeps
        overlapping regions safe — relay spans hold across awaits)."""
        if not self.enabled:
            return _NULL_SPAN
        h = self.histograms.get(name)
        if h is None:
            h = self.histogram(name)
        return _Span(h, self._clock)

    # -- export -----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready dump: {"counters": {...}, "gauges": {...},
        "histograms": {name: summary+buckets}} — the METRICS wire
        payload and the input to both renderers below."""
        return {
            "counters": {n: c.value for n, c in self.counters.items()},
            "gauges": {n: g.value for n, g in self.gauges.items()},
            "histograms": {
                n: h.snapshot() for n, h in self.histograms.items()
            },
        }


# -- renderers (pure functions of a snapshot: the CLI runs them on the
#    wire payload, with no registry of its own) ---------------------------


def _fmt_seconds(v: float | None) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def format_table(snapshot: dict) -> str:
    """The `p1 metrics` human rendering: counters, gauges, then the
    histogram latency table (p50/p95/p99/max)."""
    lines = []
    counters = snapshot.get("counters", {})
    if counters:
        width = max(len(n) for n in counters)
        lines.append("counters:")
        for name, value in counters.items():
            lines.append(f"  {name:<{width}}  {value}")
    gauges = snapshot.get("gauges", {})
    if gauges:
        width = max(len(n) for n in gauges)
        lines.append("gauges:")
        for name, value in gauges.items():
            lines.append(f"  {name:<{width}}  {value:.6g}")
    hists = snapshot.get("histograms", {})
    if hists:
        width = max(len(n) for n in hists)
        lines.append("histograms:")
        lines.append(
            f"  {'name':<{width}}  {'count':>8}  {'p50':>10}  "
            f"{'p95':>10}  {'p99':>10}  {'max':>10}"
        )
        for name, h in hists.items():
            lines.append(
                f"  {name:<{width}}  {h['count']:>8}  "
                f"{_fmt_seconds(h['p50']):>10}  "
                f"{_fmt_seconds(h['p95']):>10}  "
                f"{_fmt_seconds(h['p99']):>10}  "
                f"{_fmt_seconds(h['max']):>10}"
            )
    return "\n".join(lines) if lines else "(no metrics)"


def _prom_name(name: str) -> str:
    """Metric name -> Prometheus-legal: dots to underscores, the house
    ``_s`` seconds suffix spelled out, ``p1_`` namespace prefix."""
    out = name.replace(".", "_").replace("-", "_")
    if out.endswith("_s"):
        out = out[:-2] + "_seconds"
    return "p1_" + out


def format_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition (0.0.4) of a registry snapshot.
    Histogram buckets are emitted sparsely (only the ``le`` rows where
    samples landed, plus +Inf) — cumulative values stay correct for
    every emitted row, which is all the format requires."""
    lines = []
    for name, value in snapshot.get("counters", {}).items():
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value}")
    for name, value in snapshot.get("gauges", {}).items():
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    for name, h in snapshot.get("histograms", {}).items():
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for le, cum in h.get("buckets", []):
            le_s = "+Inf" if le == "+Inf" else repr(float(le))
            lines.append(f'{pname}_bucket{{le="{le_s}"}} {cum}')
        lines.append(f"{pname}_sum {h['sum']}")
        lines.append(f"{pname}_count {h['count']}")
    return "\n".join(lines) + "\n"


class NodeLogAdapter(logging.LoggerAdapter):
    """Log attribution for multi-node processes: prefixes every record
    with the node's identity (sim host / listen port), so `p1 net`,
    netharness, and simulator logs stop interleaving anonymously.

    ``ident`` is a zero-arg callable, not a string: a node knows its
    bound port only after ``start()``, and the adapter must follow it.
    """

    def __init__(self, logger: logging.Logger, ident):
        super().__init__(logger, {})
        self._ident = ident

    def process(self, msg, kwargs):
        return f"[{self._ident()}] {msg}", kwargs
