"""The wallet push plane: watch-filter subscriptions notified at block
connect, with failure as the design center.

A subscription is a set of watch items (account ids, txids — the same
byte strings the BIP158-analog block filters commit to) plus three
callables that abstract the session: ``send`` (enqueue one encoded
frame), ``buffer_size`` (bytes queued on the transport), ``close``
(disconnect).  The manager is deliberately transport-agnostic so the
same code pushes over real sockets (Node, QueryPlaneServer), simulated
transports (chaos), and in-process sinks (benchmarks) — the write
buffer IS the per-session queue, bounded by the same governor caps that
bound every other session.

Slow consumers degrade down a ladder instead of ballooning the write
gauge:

  coalesce   buffer > SUB_COALESCE_BYTES: non-matching header events
             are skipped (the wallet bridges the hole from the
             filter-header commitment chain); matches still go out.
  drop       buffer > drop_bytes: nothing goes out; the first dropped
             height is remembered and a single GAP event is emitted
             when the buffer drains, telling the wallet exactly which
             window to replay (its resume cursor stays valid).
  disconnect buffer > hard_bytes: the session is closed — same
             hard-cap-means-disconnect contract as node peers.

Trust model: events carry the full filter plus its commitment header
(``filter_header[i] = H(filter_hash[i] || filter_header[i-1])``), so a
wallet verifies linkage and re-matches locally; this plane never asks
to be believed.  Resume cursors are (height, filter_header) pairs and
are *verified* against the committed chain before replay — a cursor
the server cannot prove (pruned window, rebased chain, or a wallet
that last spoke to a liar) is refused by closing the session, which is
the wallet's signal to fail over to an archive replica.

Per-block match cost is O(filter decode + subs · items), not
O(subs · filter): the filter is decoded once into a value set and each
subscriber probes it (``filters.matches_values``), which is what makes
100k live subscriptions per host a benchmark number instead of a wish.
"""

from __future__ import annotations

import time

from ..chain.filters import decode_value_set, filter_count, matches_values
from .protocol import BlockEvent, encode_event, encode_event_gap
from .governor import WRITE_QUEUE_GOSSIP_MAX, WRITE_QUEUE_MAX

# Buffer thresholds for the degradation ladder.  Coalesce kicks in well
# below the gossip soft cap so a merely-laggy wallet sheds header noise
# before it starts losing matches; drop reuses the gossip soft cap and
# disconnect the session hard cap, so one stalled subscriber can squat
# at most the same memory as one stalled peer.
SUB_COALESCE_BYTES = 256 << 10
SUB_DROP_BYTES = WRITE_QUEUE_GOSSIP_MAX
SUB_HARD_BYTES = WRITE_QUEUE_MAX

# Recent (height -> block hash) ring used to detect reorgs of already
# notified heights.  Deeper reorgs than this are re-pushed from the
# ring's floor; wallets verify linkage anyway.
_SENT_RING = 256

_OK = 0
_DROPPED = 1
_DEAD_HARD = 2
_DEAD_ERR = 3


class Subscription:
    """One live session's watch registration."""

    __slots__ = ("key", "items", "send", "buffer_size", "close", "gap_start", "coalesced")

    def __init__(self, key, items, send, buffer_size, close):
        self.key = key
        self.items = tuple(items)
        self.send = send
        self.buffer_size = buffer_size
        self.close = close
        self.gap_start: int | None = None
        self.coalesced = 0


class _HeightParts:
    """Everything notify needs for one connected height, built once and
    shared across every subscriber."""

    __slots__ = ("height", "bhash", "raw_header", "fheader", "filter", "values", "count", "index", "plain")

    def __init__(self, height, bhash, raw_header, fheader, fbytes, index):
        self.height = height
        self.bhash = bhash
        self.raw_header = raw_header
        self.fheader = fheader
        self.filter = fbytes
        self.values = decode_value_set(fbytes)
        self.count = filter_count(fbytes)
        self.index = index
        self.plain = encode_event(
            BlockEvent(height=height, raw_header=raw_header, filter_header=fheader,
                       filter=fbytes, matched=False, txids=())
        )


class SubscriptionManager:
    """Pushes block-connect events to registered watchers from a source.

    ``source`` is duck-typed with: ``tip_height`` (int property),
    ``hash_at(h)``, ``raw_header_at(h)``, ``filter_at(h)``,
    ``fheader_at(h)`` (each -> bytes | None), and
    ``block_items_at(h)`` -> dict[item_bytes, tuple[txid, ...]] | None
    (None when the block body is unavailable — matches then fall back
    to the probabilistic filter, txids empty, exactly the information a
    pruned replica honestly has).
    """

    def __init__(self, source, *, clock=time.monotonic, registry=None,
                 coalesce_bytes: int = SUB_COALESCE_BYTES,
                 drop_bytes: int = SUB_DROP_BYTES,
                 hard_bytes: int = SUB_HARD_BYTES):
        self._source = source
        self._clock = clock
        self._registry = registry
        self._coalesce_bytes = coalesce_bytes
        self._drop_bytes = drop_bytes
        self._hard_bytes = hard_bytes
        self._subs: dict = {}
        self._sent: dict[int, bytes] = {}
        self._next_height = 0
        # Ladder + lifecycle counters; ints here are the source of
        # truth, the registry only mirrors the latency histogram and
        # point-in-time gauges.
        self.events_pushed = 0
        self.events_coalesced = 0
        self.events_dropped = 0
        self.gap_events = 0
        self.replayed = 0
        self.disconnects_hard = 0
        self.disconnects_error = 0
        self.cursor_rejects = 0
        self.subscribed_total = 0
        self.drained_total = 0
        self.queue_depth_bytes = 0
        # History before this manager existed was never promised to
        # anyone — start the cursor at the source's current tip.
        self.reset_cursor()

    # -- registration -------------------------------------------------

    def __len__(self) -> int:
        return len(self._subs)

    @property
    def notified_height(self) -> int:
        return self._next_height - 1

    def reset_cursor(self) -> None:
        """Fast-forward to the source tip without building events —
        the boot/resume seam (a node that replayed its store grew the
        chain with nobody subscribed) and the idle fast path."""
        tip = self._source.tip_height
        self._next_height = tip + 1
        bhash = self._source.hash_at(tip)
        self._sent.clear()
        if bhash is not None:
            self._sent[tip] = bhash

    async def subscribe(self, key, items, cursor, *, send, buffer_size, close) -> bool:
        """Register a watcher; replay the committed window past ``cursor``
        first so the stream is gap-free from the wallet's last verified
        point.  Returns False (caller should close the session) when the
        cursor cannot be verified against the commitment chain."""
        old = self._subs.pop(key, None)
        if old is not None:
            self._gauge_live()
        sub = Subscription(key, items, send, buffer_size, close)
        if cursor is not None:
            start, cursor_fheader = cursor
            committed = self._source.fheader_at(start)
            if committed is None or committed != cursor_fheader:
                self.cursor_rejects += 1
                return False
            replay_from = start + 1
            # Replay everything already notified, then register.  The
            # catch-up loop re-checks because a block can connect while
            # replay sends are in flight; registration happens with no
            # await between the last replayed height and the insert, so
            # live pushes take over exactly where replay stopped.
            while True:
                target = self._next_height - 1
                if replay_from > target:
                    break
                for h in range(replay_from, target + 1):
                    parts = self._build(h)
                    if parts is None:
                        break
                    state = await self._deliver(sub, parts)
                    if state in (_DEAD_HARD, _DEAD_ERR):
                        self._count_dead(state)
                        return True
                    if state is _OK:
                        self.replayed += 1
                replay_from = target + 1
        self._subs[key] = sub
        self.subscribed_total += 1
        self._gauge_live()
        return True

    def unsubscribe(self, key) -> bool:
        sub = self._subs.pop(key, None)
        self._gauge_live()
        return sub is not None

    def drop(self, key) -> None:
        """Forget a watcher whose session died externally."""
        self._subs.pop(key, None)
        self._gauge_live()

    def close_all(self) -> None:
        for sub in list(self._subs.values()):
            try:
                sub.close()
            except Exception:
                pass
        self._subs.clear()
        self._gauge_live()

    async def drain(self) -> int:
        """Graceful shutdown (`p1 serve` on SIGTERM): push one final
        EVENTGAP carrying the next-to-come height to every live
        subscriber, then close them all; returns how many were drained.
        The wallet reads the gap as "this window will not arrive here —
        replay it elsewhere": its (height, filter_header) resume cursor
        stays exactly where its last verified event left it, so failover
        after a drain is gap-free by the same argument as failover after
        a crash, minus the dead-socket wait."""
        nxt = self._next_height
        drained = 0
        for sub in list(self._subs.values()):
            try:
                await sub.send(encode_event_gap(nxt, nxt))
            except Exception:
                pass
            try:
                sub.close()
            except Exception:
                pass
            drained += 1
        self._subs.clear()
        self.drained_total += drained
        self._gauge_live()
        return drained

    # -- notification -------------------------------------------------

    async def notify(self) -> None:
        """Push every newly connected (or reorged) height to all
        subscribers.  Safe to call redundantly; a no-op when the source
        tip has not moved."""
        if not self._subs:
            # Nobody listening: keep the cursor current so the first
            # subscriber starts from NOW, not from a replay of every
            # height connected while the room was empty.
            self.reset_cursor()
            return
        tip = self._source.tip_height
        h = min(self._next_height - 1, tip)
        while h >= 0:
            sent = self._sent.get(h)
            if sent is None or sent == self._source.hash_at(h):
                break
            h -= 1
        start = h + 1
        if start > tip:
            self._gauge_depth()
            return
        t0 = self._clock()
        for height in range(start, tip + 1):
            parts = self._build(height)
            if parts is None:
                break  # filter not committed yet (pruned body); retry on next connect
            await self._push_height(parts)
            self._sent[height] = parts.bhash
            self._next_height = height + 1
            floor = height - _SENT_RING
            while self._sent and min(self._sent) < floor:
                del self._sent[min(self._sent)]
        if self._registry is not None:
            self._registry.observe("subs.notify_s", self._clock() - t0)

    def _build(self, height):
        src = self._source
        bhash = src.hash_at(height)
        raw = src.raw_header_at(height)
        fheader = src.fheader_at(height)
        fbytes = src.filter_at(height)
        if bhash is None or raw is None or fheader is None or fbytes is None:
            return None
        return _HeightParts(height, bhash, raw, fheader, fbytes, src.block_items_at(height))

    def _match(self, parts, items):
        index = parts.index
        if index is not None:
            txids: list[bytes] = []
            for it in items:
                txids.extend(index.get(it, ()))
            if txids:
                return True, tuple(dict.fromkeys(txids))
        if matches_values(parts.values, parts.count, parts.bhash, items):
            return True, ()
        return False, ()

    async def _deliver(self, sub, parts) -> int:
        try:
            buf = sub.buffer_size()
        except Exception:
            return _DEAD_ERR
        if buf > self.queue_depth_bytes:
            self.queue_depth_bytes = buf
        if buf >= self._hard_bytes:
            return _DEAD_HARD
        if buf >= self._drop_bytes:
            if sub.gap_start is None:
                sub.gap_start = parts.height
            self.events_dropped += 1
            return _DROPPED
        matched, txids = self._match(parts, sub.items)
        try:
            if sub.gap_start is not None:
                await sub.send(encode_event_gap(sub.gap_start, parts.height - 1))
                sub.gap_start = None
                self.gap_events += 1
            if matched:
                payload = encode_event(
                    BlockEvent(height=parts.height, raw_header=parts.raw_header,
                               filter_header=parts.fheader, filter=parts.filter,
                               matched=True, txids=txids)
                )
            elif buf >= self._coalesce_bytes:
                sub.coalesced += 1
                self.events_coalesced += 1
                return _OK
            else:
                payload = parts.plain
            await sub.send(payload)
        except Exception:
            return _DEAD_ERR
        self.events_pushed += 1
        return _OK

    async def _push_height(self, parts) -> None:
        dead: list[tuple[object, int]] = []
        self.queue_depth_bytes = 0
        for key, sub in list(self._subs.items()):
            state = await self._deliver(sub, parts)
            if state in (_DEAD_HARD, _DEAD_ERR):
                dead.append((key, state))
        for key, state in dead:
            sub = self._subs.pop(key, None)
            if sub is not None:
                self._count_dead(state)
                try:
                    sub.close()
                except Exception:
                    pass
        if dead:
            self._gauge_live()
        self._gauge_depth()

    def _count_dead(self, state: int) -> None:
        if state is _DEAD_HARD:
            self.disconnects_hard += 1
        else:
            self.disconnects_error += 1

    # -- telemetry ----------------------------------------------------

    def _gauge_live(self) -> None:
        if self._registry is not None:
            self._registry.gauge("subs.live").set(float(len(self._subs)))

    def _gauge_depth(self) -> None:
        if self._registry is not None:
            self._registry.gauge("subs.queue_depth_bytes").set(float(self.queue_depth_bytes))

    def snapshot(self) -> dict:
        return {
            "live": len(self._subs),
            "subscribed_total": self.subscribed_total,
            "events_pushed": self.events_pushed,
            "events_coalesced": self.events_coalesced,
            "events_dropped": self.events_dropped,
            "gap_events": self.gap_events,
            "replayed": self.replayed,
            "disconnects_hard": self.disconnects_hard,
            "disconnects_error": self.disconnects_error,
            "cursor_rejects": self.cursor_rejects,
            "drained_total": self.drained_total,
            "queue_depth_bytes": self.queue_depth_bytes,
        }


def block_items_index(block) -> dict:
    """item bytes -> (txid, ...) for one block: every txid plus every
    sender/recipient account id (utf-8) — exactly the item universe the
    block's filter commits to (chain/filters.py ``filter_items``), so
    an exact-index hit and a filter probe agree on what is watchable."""
    index: dict[bytes, tuple] = {}
    for tx in block.txs:
        txid = tx.txid()
        for item in (txid, tx.sender.encode("utf-8"), tx.recipient.encode("utf-8")):
            prev = index.get(item)
            index[item] = prev + (txid,) if prev else (txid,)
    return index


class ChainSubSource:
    """Adapter: a ``chain.Chain`` (with its ``filter_headers``
    commitment chain and ``filter_index``) as a notification source."""

    __slots__ = ("_chain_ref",)

    def __init__(self, chain):
        # A zero-arg callable late-binds the chain: the node REPLACES
        # ``self.chain`` on store/snapshot resume and live re-base, and
        # the push plane must follow it, not a stale object.
        self._chain_ref = chain if callable(chain) else (lambda: chain)

    @property
    def _chain(self):
        return self._chain_ref()

    @property
    def tip_height(self) -> int:
        return min(self._chain.height, self._chain.filter_headers.tip_height)

    def hash_at(self, height):
        return self._chain.main_hash_at(height)

    def raw_header_at(self, height):
        bhash = self._chain.main_hash_at(height)
        if bhash is None:
            return None
        header = self._chain.header_of(bhash)
        if header is None:
            return None
        return header.serialize()

    def filter_at(self, height):
        bhash = self._chain.main_hash_at(height)
        if bhash is None:
            return None
        return self._chain.block_filter(bhash)

    def fheader_at(self, height):
        return self._chain.filter_headers.header_at(height)

    def block_items_at(self, height):
        bhash = self._chain.main_hash_at(height)
        if bhash is None or not self._chain.body_available(bhash):
            return None
        blk = self._chain.get(bhash)
        if blk is None:
            return None
        return block_items_index(blk)
