"""P2P wire protocol: length-prefixed binary frames.

Capability parity: the reference's gossip protocol (BASELINE.json:5,10).
Frame = 4-byte big-endian payload length + 1-byte message type + payload.
Deterministic binary payloads reuse the core serializers, so a message's
bytes are exactly the consensus bytes — nothing to re-canonicalize.

Messages:

- HELLO:     genesis hash (32) + tip height (4) + listen port (2) + u64
             instance nonce (random per node process — a node that
             receives its OWN nonce back just dialed itself via a
             gossiped address and drops the connection, Bitcoin's
             self-connect detection).  Sent both ways on connect; genesis
             mismatch = disconnect.
- BLOCK:     f64 sender wall-clock send time + one serialized block (push
             gossip).  The timestamp is *telemetry only* — receivers use
             it to measure propagation delay (send -> accept), never for
             consensus.  Clocks are trusted to the extent NTP keeps hosts
             in sync; the benchmark topology is localhost, where the skew
             is zero by construction (SURVEY §5 gossip round-trip timing).
- TX:        one serialized transaction (push gossip).
- GETBLOCKS: u16 count + count * 32-byte locator hashes (sync request).
             Requester-side contract (not wire-visible): every
             multi-round fetch — this, GETBLOCKTXN, paged GETMEMPOOL,
             and the light client's GETHEADERS loop — runs under
             request supervision (node/supervision.py): the requester
             holds a *progress* deadline over the round and re-issues
             to a different peer when nothing advances, so serving
             slowly-but-surely is always safe while serving nothing
             (however chattily) forfeits the sync to someone else.
- BLOCKS:    u16 count + count * (u32 len + serialized block) (sync reply).
- GETMEMPOOL: empty body (start of sync) or u64 fee + 32-byte txid — the
             stable cursor of the last transaction already received; the
             reply covers fee-descending (txid-ascending) keys strictly
             after it.
- GETACCOUNT: u8 len + account id bytes — query one account's consensus
             state at the peer's tip (balance, nonce) plus the next
             usable seq net of the peer's own pending pool (what a wallet
             should sign next).  Serves `p1 account` and `p1 tx`'s
             auto-seq.
- ACCOUNT:   u8 len + account + u64 balance + u64 nonce + u64 next_seq +
             u32 tip height (the reply's reference point).
- MEMPOOL:   u8 more + u16 count + count * (u16 len + serialized tx).
             Late joiners learn in-flight transactions this way
             (blocks-only sync would leave their pools empty); pools
             larger than one reply continue while ``more`` is set.  A key
             cursor, not a positional one: pool churn between pages can't
             skip entries, and the requester enforces strictly-advancing
             cursors so a hostile responder can't loop it.
- GETPROOF:  32-byte txid — request an SPV inclusion proof for a
             main-chain-confirmed transaction (`p1 proof`).
- PROOF:     u8 found; if found: u32 height + u32 tip height + u32 tx
             index + 80-byte header + u16 branch count + count * 32-byte
             merkle siblings + u16 tx len + serialized tx.  The client
             verifies PoW + merkle branch + tx validity itself
             (p1_tpu/chain/proof.py) — the reply is evidence, not an
             assertion to trust.
- CBLOCK:    compact block push (BIP152's idea, full-txid form): f64 send
             timestamp + 80-byte header + u16 ntx + u16 n_prefilled +
             n_prefilled * (u16 index + u32 len + raw tx) + one 32-byte
             txid per remaining transaction, in block order.  The sender
             prefills what receivers cannot have (the coinbase); the
             receiver reconstructs the rest from its mempool — txids are
             full SHA-256d hashes of the exact wire bytes, so a match IS
             the transaction (no BIP152 short-id collision handling
             needed) — and fetches whatever it lacks with GETBLOCKTXN.
- GETBLOCKTXN: 32-byte block hash + u16 count + count * u16 ascending tx
             indices the requester could not reconstruct.
- BLOCKTXN:  32-byte block hash + u16 count + count * (u32 len + raw tx)
             answering a GETBLOCKTXN, same index order as requested.
- GETADDR:   empty body — ask a peer for addresses of other nodes it
             knows (peer discovery; asked once per session).
- ADDR:      u16 count + count * (u16 port + u8 len + utf-8 host) —
             known listening addresses.  Receivers merge them into a
             bounded address book; with ``--target-peers N`` set a node
             dials discovered addresses until it holds N connections, so
             a new node bootstraps the whole network from one seed peer.
- GETFEES:   u16 window (blocks to sample; 0 = server default) — fee
             estimation query (`p1 fees`, `p1 tx --fee auto`).
- FEES:      u16 window used + u32 sample count + u64 p25/p50/p75 fee
             percentiles over transfers confirmed in the window + u32 tip
             height.  Confirmed fees only: what actually cleared, not the
             pending bid book.
- PING:      u64 nonce — keepalive probe.  A node that has heard nothing
             from a peer for its idle interval sends one; ANY frame (not
             just the PONG) counts as liveness, so a busy peer never
             wastes a round trip.  A peer that stays silent through the
             probe's answer window is evicted and its slot reused — the
             liveness layer every Bitcoin-family node carries, without
             which 64 cheap silent sockets pin MAX_PEERS forever.
- PONG:      u64 nonce echoed from the PING.  Tooling clients answer too
             (node/client.py) so a slow SPV sync isn't evicted as dead.
- GETHEADERS: u16 count + count * 32-byte locator hashes — headers-first
             sync for light clients (`p1 headers`): same locator
             semantics as GETBLOCKS, but the reply carries bare headers.
- GETSTATUS: empty body — operator probe (`p1 status`): ask a running
             node for its full status JSON (height, peers, sync/storage/
             overload state).  Served even in the SHED overload state:
             overload must stay observable while it is happening.
- STATUS:    the node's ``status()`` dict as canonical JSON (utf-8).
             Deliberately JSON, not a packed layout — the status surface
             grows every round and must not cost a version bump per
             field.
- HEADERS:   u16 count + count * 80-byte serialized headers, main chain
             ascending from the first recognized locator hash.  A light
             client iterates GETHEADERS until the reply is empty, then
             verifies the whole chain itself (replay_host — PoW, linkage,
             and the retarget difficulty schedule), needing ~80 B/block
             instead of full blocks and trusting nothing but work.
- GETFILTERS: u32 start height + u16 count — request the compact block
             filters (chain/filters.py, BIP158 analog) for a main-chain
             height range.  A light client that has synced headers
             downloads the filter stream, matches its own accounts/txids
             LOCALLY, and fetches only the (rare) matching blocks — sync
             by filter match instead of per-address queries.
- FILTERS:   u32 start height + u16 count + count * (32-byte block hash
             + u32 filter len + filter bytes), heights ascending from
             the requested start.  The block hash lets the client pin
             each filter to its independently verified header chain; the
             filter itself is a Golomb-coded set over the block's txids
             and account ids with zero false negatives (a non-match is
             proof of absence).  The server caps ``count`` like the
             other range queries — ask again from where the reply ended.
- GETSNAPSHOT: u32 start chunk + u16 count — snapshot-state sync
             (chain/snapshot.py).  count 0 asks for the MANIFEST
             (height, block hash, state root, per-chunk digests, the
             full anchor block); count >= 1 asks for that chunk range.
             Served range-capped and governor-admitted like every
             other query; an ASSUMED node answers "none" (it must not
             relay state it has not itself validated).
- GETMETRICS: empty body — telemetry probe (`p1 metrics`): ask a node
             (or a `p1 serve` replica) for its metrics registry snapshot
             (node/telemetry.py — counters, gauges, per-stage latency
             histograms).  Unlike GETSTATUS it IS shed under overload:
             the status probe is the minimal health signal and stays up;
             the full latency export is a capacity consumer an
             overloaded node may refuse.
- METRICS:   the registry snapshot as canonical JSON (utf-8) — same
             growth-without-version-bump rationale as STATUS.
- SNAPSHOT:  u8 kind — 0 none (no snapshot available), 1 manifest
             (u32 len + manifest payload), 2 chunks (u32 start + u16
             count + count * (u32 len + chunk payload)).  Everything
             inside is checkable against the manifest: the receiver
             verifies each chunk's digest AS IT ARRIVES and the state
             root at the end — a peer lying mid-transfer is caught on
             the first bad chunk.  The payloads are exactly the
             snapshot-file records, so wire and disk cannot drift.
- GETMAINTAIN: a maintenance command as canonical JSON (utf-8):
             ``{"op": "status"}`` reports the maintenance plane
             (version-bits deployment states, rebase/prune/compact
             counters, busy flag); ``{"op": "rebase", "keep": N}``,
             ``{"op": "prune", "keep": N}`` and ``{"op": "compact"}``
             run the corresponding zero-downtime operation on a live
             node (`p1 maintain`).  JSON like STATUS: the operator
             surface grows, the wire version must not.
- MAINTAIN:  the maintenance reply as canonical JSON — ``{"ok": bool,
             ...}`` with op-specific fields (the rebase result, prune
             floor, compaction stats, or the status report).  Errors
             come back as ``{"ok": false, "error": "..."}`` rather
             than a dropped session: a refused maintenance command is
             an answer, not a protocol violation.
- REQRECON:  u8 full + u32 set size — open one set-reconciliation round
             (node/reconcile.py, the Erlay-analog relay plane): "my
             pending-announcement window for you holds N short IDs;
             sketch yours".  ``full`` = 1 asks the responder to sketch
             its WHOLE pool (the initial mempool sync sharing the
             short-ID machinery) instead of just the pending window.
             The set size feeds the responder's capacity estimate; it
             is advisory, never trusted past the capacity clamp.
- SKETCH:    u32 set size + u16 word count + words * u32 syndromes —
             the reconciliation sketch reply (word count is capped at
             MAX_CAPACITY + 1; anything larger is a protocol
             violation, the sketch-poisoning bound).  The requester
             XORs its own equal-capacity sketch over the same salted
             short-ID space and decodes the symmetric difference.
- RECONCILDIFF: u8 success + u16 count + count * u32 short IDs — the
             round-closing frame from the initiator.  success=1 lists
             the decoded difference (the responder serves its side as
             TX pushes and clears its frozen window); success=0 means
             the difference exceeded the sketch capacity or the bytes
             did not decode — both sides fall back to FLOOD for the
             frozen window, so reconciliation failure costs bandwidth,
             never transactions.
- GETTX:     u16 count + count * u32 short IDs — fetch transactions by
             salted short ID (the fallback/fetch half of the exchange:
             the initiator asks for diff elements it cannot map
             locally).  Unknown IDs are skipped, not errors — a missed
             tx arrives on a later round or in a block.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import struct
import time

from p1_tpu.chain.proof import TxProof
from p1_tpu.core.block import Block
from p1_tpu.core.header import HEADER_SIZE, BlockHeader
from p1_tpu.core.tx import Transaction
from p1_tpu.node.reconcile import MAX_CAPACITY as RECON_MAX_CAPACITY

class ProtocolError(ValueError):
    """The peer sent bytes that violate the protocol (malformed frame,
    wrong version, unparsable payload).  A dedicated subclass so the
    node's misbehavior scoring can tell PEER-side faults apart from
    ValueErrors raised by our own encode paths — only the former may
    count against the remote."""


class ChainMismatch(ProtocolError):
    """A well-formed HELLO for the wrong chain or protocol version.
    Still ends the session — but as *misconfiguration*, not hostility:
    the node's ban scoring must ignore it, or three wallet invocations
    with the wrong --difficulty/retarget flags inside the scoring window
    would ban 127.0.0.1 and refuse a whole localhost mesh (ADVICE r4).
    Ban scores are reserved for malformed bytes and forgeries."""


MAX_FRAME = 32 << 20  # hard cap against hostile length prefixes
_LEN = struct.Struct(">I")
#: Wire protocol version, carried in HELLO.  Bump when the message surface
#: changes incompatibly — layout changes (v2: BLOCK gained the f64
#: telemetry timestamp, transactions gained chain/pubkey/sig fields) but
#: also pure additions (v3: GETPROOF/PROOF): HELLO enforces strict version
#: equality, so bumping on additions means a mixed-version pair fails the
#: handshake with a clear error instead of dying mid-session the first
#: time the newer side queries a message the older one calls a protocol
#: violation.  Round 3 spoke an unversioned HELLO; its frames fail here as
#: "bad HELLO size".  v4 added compact block relay (CBLOCK/GETBLOCKTXN/
#: BLOCKTXN); v5 headers-first sync (GETHEADERS/HEADERS); v6 peer
#: discovery (GETADDR/ADDR + the HELLO instance nonce); v7 fee
#: estimation (GETFEES/FEES); v8 liveness (PING/PONG + handshake/idle
#: deadlines — a v7 node would call the probe a protocol violation); v9
#: the operator status probe (GETSTATUS/STATUS — `p1 status` renders a
#: running node's full status JSON, overload block included); v10 the
#: query serving plane (GETFILTERS/FILTERS — compact block filters for
#: light-client sync by filter match, chain/filters.py); v11 untrusted
#: snapshot sync (GETSNAPSHOT/SNAPSHOT — chunked ledger-state snapshots
#: with a self-describing manifest, chain/snapshot.py); v12 the
#: telemetry plane (GETMETRICS/METRICS — the metrics registry snapshot
#: of node/telemetry.py, served by nodes and replicas); v13 the
#: maintenance plane (GETMAINTAIN/MAINTAIN — `p1 maintain` drives live
#: re-basing, online prune/compact, and version-bits status on a
#: running node without restarting it); v14 the wallet push plane
#: (SUBSCRIBE/EVENT/UNSUBSCRIBE — watch-filter subscriptions pushed at
#: block connect with gap-free resume cursors — plus GETFILTERHEADERS/
#: FILTERHEADERS, the BIP157-analog filter-header commitment chain a
#: wallet cross-checks untrusted filter streams against); v15 the
#: bandwidth-scale relay plane (REQRECON/SKETCH/RECONCILDIFF/GETTX —
#: Erlay-analog set-reconciliation tx gossip over salted short IDs,
#: node/reconcile.py, with flood kept as the fallback and for block
#: announces).
PROTOCOL_VERSION = 15
_HELLO = struct.Struct(">B32sIHQ")


class MsgType(enum.IntEnum):
    """One byte after the length prefix.  Every member must thread the
    whole wire contract — encoder, ``_decode`` arm, ``_dispatch`` arm,
    admission class (node.py ``_MSG_CLASS``/``_ADMISSION_EXEMPT``),
    SHED classification (``_SHED_DROPS``/``_SHED_KEEPS``), and a
    ``MSG_SINCE`` version row — enforced structurally by the
    ``wire-contract`` lint rule and at import by the asserts beside
    each table."""

    HELLO = 1
    BLOCK = 2
    TX = 3
    GETBLOCKS = 4
    BLOCKS = 5
    GETMEMPOOL = 6
    MEMPOOL = 7
    GETACCOUNT = 8
    ACCOUNT = 9
    GETPROOF = 10
    PROOF = 11
    CBLOCK = 12
    GETBLOCKTXN = 13
    BLOCKTXN = 14
    GETHEADERS = 15
    HEADERS = 16
    GETADDR = 17
    ADDR = 18
    GETFEES = 19
    FEES = 20
    PING = 21
    PONG = 22
    GETSTATUS = 23
    STATUS = 24
    GETFILTERS = 25
    FILTERS = 26
    GETSNAPSHOT = 27
    SNAPSHOT = 28
    GETMETRICS = 29
    METRICS = 30
    GETMAINTAIN = 31
    MAINTAIN = 32
    SUBSCRIBE = 33
    EVENT = 34
    UNSUBSCRIBE = 35
    GETFILTERHEADERS = 36
    FILTERHEADERS = 37
    REQRECON = 38
    SKETCH = 39
    RECONCILDIFF = 40
    GETTX = 41


#: The wire version that introduced each frame type — the version-gate
#: half of the wire contract.  HELLO enforces strict version equality,
#: so this table is not a negotiation surface; it is the AUDITABLE
#: history the module docstring used to carry only in prose, and the
#: ``wire-contract`` lint rule fails any member without a row (or any
#: row claiming a version newer than ``PROTOCOL_VERSION`` — a frame
#: cannot ship ahead of its version bump).
MSG_SINCE: dict[MsgType, int] = {
    # the v1/v2 baseline surface (round 3's unversioned protocol,
    # retroactively v1; BLOCK's telemetry stamp and the tx field
    # extensions were the v2 layout change)
    MsgType.HELLO: 1,
    MsgType.BLOCK: 1,
    MsgType.TX: 1,
    MsgType.GETBLOCKS: 1,
    MsgType.BLOCKS: 1,
    MsgType.GETMEMPOOL: 1,
    MsgType.MEMPOOL: 1,
    MsgType.GETACCOUNT: 1,
    MsgType.ACCOUNT: 1,
    MsgType.GETPROOF: 3,
    MsgType.PROOF: 3,
    MsgType.CBLOCK: 4,
    MsgType.GETBLOCKTXN: 4,
    MsgType.BLOCKTXN: 4,
    MsgType.GETHEADERS: 5,
    MsgType.HEADERS: 5,
    MsgType.GETADDR: 6,
    MsgType.ADDR: 6,
    MsgType.GETFEES: 7,
    MsgType.FEES: 7,
    MsgType.PING: 8,
    MsgType.PONG: 8,
    MsgType.GETSTATUS: 9,
    MsgType.STATUS: 9,
    MsgType.GETFILTERS: 10,
    MsgType.FILTERS: 10,
    MsgType.GETSNAPSHOT: 11,
    MsgType.SNAPSHOT: 11,
    MsgType.GETMETRICS: 12,
    MsgType.METRICS: 12,
    MsgType.GETMAINTAIN: 13,
    MsgType.MAINTAIN: 13,
    MsgType.SUBSCRIBE: 14,
    MsgType.EVENT: 14,
    MsgType.UNSUBSCRIBE: 14,
    MsgType.GETFILTERHEADERS: 14,
    MsgType.FILTERHEADERS: 14,
    MsgType.REQRECON: 15,
    MsgType.SKETCH: 15,
    MsgType.RECONCILDIFF: 15,
    MsgType.GETTX: 15,
}
assert set(MSG_SINCE) == set(MsgType), "every frame type needs a version row"
assert all(1 <= v <= PROTOCOL_VERSION for v in MSG_SINCE.values())


@dataclasses.dataclass(frozen=True)
class AccountState:
    account: str
    balance: int
    nonce: int  # confirmed transfers at the tip (consensus nonce)
    next_seq: int  # nonce + the peer's own pending spends (what to sign next)
    tip_height: int


@dataclasses.dataclass(frozen=True)
class CompactBlock:
    """Decoded CBLOCK: everything needed to reconstruct the block from a
    mempool — or to know exactly which transactions to fetch."""

    sent_ts: float
    header: BlockHeader
    ntx: int
    prefilled: tuple[tuple[int, Transaction], ...]  # (index, tx) ascending
    txids: tuple[bytes, ...]  # remaining transactions, block order


@dataclasses.dataclass(frozen=True)
class FeeStats:
    """Decoded FEES reply: confirmed-fee percentiles at the peer's tip."""

    window_blocks: int
    samples: int
    p25: int
    p50: int
    p75: int
    tip_height: int


@dataclasses.dataclass(frozen=True)
class BlockEvent:
    """One decoded push-plane EVENT (v14): everything a subscribed
    wallet needs to verify the notification before believing it — the
    raw header (PoW + linkage), the filter bytes (re-match locally) and
    the filter header (check the commitment chain).  ``matched`` and
    ``txids`` are the server's *claim* about the session's watch set; a
    trustless client treats them as hints and re-derives both."""

    height: int
    raw_header: bytes  # 80 bytes, serialized
    filter_header: bytes  # 32-byte commitment at this height
    filter: bytes  # the block's compact filter
    matched: bool  # server's claim: filter matched the watch set
    txids: tuple[bytes, ...]  # server's claim: confirmed watched txids


@dataclasses.dataclass(frozen=True)
class GapEvent:
    """A push-plane degradation notice: events for heights
    ``[start, end]`` were dropped (the slow-consumer drop-to-cursor
    rung).  The session stays live; the client owes itself a replay of
    the window — from this server or any other replica, the commitment
    chain makes them interchangeable."""

    start: int
    end: int


@dataclasses.dataclass(frozen=True)
class Hello:
    genesis_hash: bytes
    tip_height: int
    listen_port: int
    #: Random per-process id; lets a node recognize (and drop) a dial to
    #: itself.  0 = one-shot tooling clients that never listen.
    nonce: int = 0


def encode_hello(h: Hello) -> bytes:
    return bytes([MsgType.HELLO]) + _HELLO.pack(
        PROTOCOL_VERSION, h.genesis_hash, h.tip_height, h.listen_port, h.nonce
    )


def encode_block(block: Block, sent_ts: float | None = None) -> bytes:
    # ``serialize`` is memoized on the block (core/block.py): relaying a
    # block that arrived by gossip re-frames the SAME wire bytes — the
    # zero-repack pipeline's relay leg.
    #
    # ``sent_ts`` is the sender's wall clock for the receiver's
    # propagation telemetry; None encodes 0.0 = "no stamp" (receivers
    # skip the sample).  The codec deliberately reads NO clock of its
    # own: stamps come from the caller's (possibly virtual) transport
    # clock, which is what keeps simulated traces byte-identical.
    ts = 0.0 if sent_ts is None else sent_ts
    return bytes([MsgType.BLOCK]) + struct.pack(">d", ts) + block.serialize()


def encode_tx(tx: Transaction) -> bytes:
    return bytes([MsgType.TX]) + tx.serialize()


def encode_getblocks(locator: list[bytes]) -> bytes:
    if len(locator) > 0xFFFF:
        raise ValueError("locator too long")
    return (
        bytes([MsgType.GETBLOCKS])
        + struct.pack(">H", len(locator))
        + b"".join(locator)
    )


def encode_blocks(blocks: list[Block]) -> bytes:
    parts = [bytes([MsgType.BLOCKS]), struct.pack(">H", len(blocks))]
    for block in blocks:
        raw = block.serialize()
        parts.append(_LEN.pack(len(raw)))
        parts.append(raw)
    return b"".join(parts)


def encode_getaccount(account: str) -> bytes:
    raw = account.encode("utf-8")
    if not 0 < len(raw) <= 255:
        raise ValueError("account id must encode to 1..255 bytes")
    return bytes([MsgType.GETACCOUNT]) + struct.pack(">B", len(raw)) + raw


def encode_account(state: AccountState) -> bytes:
    raw = state.account.encode("utf-8")
    return (
        bytes([MsgType.ACCOUNT])
        + struct.pack(">B", len(raw))
        + raw
        + struct.pack(
            ">QQQI", state.balance, state.nonce, state.next_seq, state.tip_height
        )
    )


def encode_cblock(block: Block, sent_ts: float | None = None) -> bytes:
    """Compact form of ``block``: prefill the coinbase (receivers cannot
    have it — it is minted by this block), elide everything else to its
    txid.  ~32 bytes per transaction on the wire instead of the full
    serialization.  ``sent_ts`` as in ``encode_block``: the caller's
    stamp or 0.0 = none, never a codec-side clock read."""
    ts = 0.0 if sent_ts is None else sent_ts
    if len(block.txs) > 0xFFFF:
        # The compact form's counts are u16; consensus blocks are u32.
        # Callers fall back to the full BLOCK encoding (node.py does).
        raise ValueError("too many transactions for a compact block")
    prefilled = []
    txids = []
    for i, tx in enumerate(block.txs):
        if i == 0 and tx.is_coinbase:
            prefilled.append((i, tx))
        else:
            txids.append(tx.txid())
    parts = [
        bytes([MsgType.CBLOCK]),
        struct.pack(">d", ts),
        block.header.serialize(),
        struct.pack(">HH", len(block.txs), len(prefilled)),
    ]
    for i, tx in prefilled:
        raw = tx.serialize()
        parts.append(struct.pack(">HI", i, len(raw)))
        parts.append(raw)
    parts.extend(txids)
    return b"".join(parts)


def encode_getblocktxn(block_hash: bytes, indices: list[int]) -> bytes:
    if len(block_hash) != 32:
        raise ValueError("block hash must be 32 bytes")
    if not indices or len(indices) > 0xFFFF:
        raise ValueError("need 1..65535 indices")
    return (
        bytes([MsgType.GETBLOCKTXN])
        + block_hash
        + struct.pack(">H", len(indices))
        + struct.pack(f">{len(indices)}H", *indices)
    )


def encode_blocktxn(block_hash: bytes, raw_txs: list[bytes]) -> bytes:
    """``raw_txs`` are pre-serialized transactions in the requested index
    order."""
    if len(block_hash) != 32:
        raise ValueError("block hash must be 32 bytes")
    if len(raw_txs) > 0xFFFF:
        raise ValueError("too many transactions for one BLOCKTXN")
    parts = [
        bytes([MsgType.BLOCKTXN]),
        block_hash,
        struct.pack(">H", len(raw_txs)),
    ]
    for raw in raw_txs:
        parts.append(struct.pack(">I", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def encode_getfees(window: int = 0) -> bytes:
    if not 0 <= window <= 0xFFFF:
        raise ValueError("bad fee window")
    return bytes([MsgType.GETFEES]) + struct.pack(">H", window)


def encode_fees(stats: FeeStats) -> bytes:
    return bytes([MsgType.FEES]) + struct.pack(
        ">HIQQQI",
        stats.window_blocks,
        stats.samples,
        stats.p25,
        stats.p50,
        stats.p75,
        stats.tip_height,
    )


def encode_getaddr() -> bytes:
    return bytes([MsgType.GETADDR])


def encode_getstatus() -> bytes:
    return bytes([MsgType.GETSTATUS])


def encode_status(status: dict) -> bytes:
    """The node's ``status()`` dict as canonical JSON (v9, `p1 status`).
    JSON rather than a packed layout: the status surface grows every
    round, and an operator probe should never be the reason a field
    addition bumps the wire version."""
    import json

    return bytes([MsgType.STATUS]) + json.dumps(
        status, separators=(",", ":")
    ).encode("utf-8")


def encode_getmetrics() -> bytes:
    return bytes([MsgType.GETMETRICS])


def encode_metrics(snapshot: dict) -> bytes:
    """A metrics registry snapshot (node/telemetry.py) as canonical
    JSON — same shape rationale as STATUS: the metric catalog grows
    every round and must not cost a wire version per addition."""
    import json

    return bytes([MsgType.METRICS]) + json.dumps(
        snapshot, separators=(",", ":")
    ).encode("utf-8")


def encode_getmaintain(command: dict) -> bytes:
    """A maintenance command (v13, `p1 maintain`) as canonical JSON —
    ``{"op": "status"|"rebase"|"prune"|"compact", ...}``.  JSON for the
    same reason as STATUS: operator surfaces grow every round and must
    not cost a wire version per field."""
    import json

    return bytes([MsgType.GETMAINTAIN]) + json.dumps(
        command, separators=(",", ":")
    ).encode("utf-8")


def encode_maintain(reply: dict) -> bytes:
    """The maintenance reply — ``{"ok": bool, ...}``; refusals travel
    as ``{"ok": false, "error": ...}``, never as dropped sessions."""
    import json

    return bytes([MsgType.MAINTAIN]) + json.dumps(
        reply, separators=(",", ":")
    ).encode("utf-8")


def encode_ping(nonce: int) -> bytes:
    return bytes([MsgType.PING]) + struct.pack(">Q", nonce)


def encode_pong(nonce: int) -> bytes:
    return bytes([MsgType.PONG]) + struct.pack(">Q", nonce)


def encode_addr(addrs: list[tuple[str, int]]) -> bytes:
    if len(addrs) > 0xFFFF:
        raise ValueError("too many addresses for one ADDR frame")
    parts = [bytes([MsgType.ADDR]), struct.pack(">H", len(addrs))]
    for host, port in addrs:
        raw = host.encode("utf-8")
        if not 0 < len(raw) <= 255 or not 0 < port <= 0xFFFF:
            raise ValueError(f"bad address {host}:{port}")
        parts.append(struct.pack(">HB", port, len(raw)))
        parts.append(raw)
    return b"".join(parts)


def encode_getheaders(locator: list[bytes]) -> bytes:
    if len(locator) > 0xFFFF:
        raise ValueError("locator too long")
    return (
        bytes([MsgType.GETHEADERS])
        + struct.pack(">H", len(locator))
        + b"".join(locator)
    )


def encode_headers(headers: list[BlockHeader]) -> bytes:
    if len(headers) > 0xFFFF:
        raise ValueError("too many headers for one HEADERS frame")
    return (
        bytes([MsgType.HEADERS])
        + struct.pack(">H", len(headers))
        + b"".join(h.serialize() for h in headers)
    )


def encode_headers_raw(raw_headers: list[bytes]) -> bytes:
    """HEADERS from pre-serialized 80-byte header slices — the read
    replica's zero-parse serving path (node/queryplane.py): headers come
    straight off the mmap'd store, no BlockHeader objects anywhere."""
    if len(raw_headers) > 0xFFFF:
        raise ValueError("too many headers for one HEADERS frame")
    for raw in raw_headers:
        if len(raw) != HEADER_SIZE:
            raise ValueError("raw header must be exactly 80 bytes")
    return (
        bytes([MsgType.HEADERS])
        + struct.pack(">H", len(raw_headers))
        + b"".join(raw_headers)
    )


def encode_blocks_raw(raw_blocks: list[bytes]) -> bytes:
    """BLOCKS from pre-serialized block records — the replica serves the
    store's exact record bytes without a Block object round trip."""
    if len(raw_blocks) > 0xFFFF:
        raise ValueError("too many blocks for one BLOCKS frame")
    parts = [bytes([MsgType.BLOCKS]), struct.pack(">H", len(raw_blocks))]
    for raw in raw_blocks:
        parts.append(_LEN.pack(len(raw)))
        parts.append(raw)
    return b"".join(parts)


def encode_getfilters(start_height: int, count: int) -> bytes:
    if not 0 <= start_height <= 0xFFFFFFFF:
        raise ValueError("bad filter start height")
    if not 0 < count <= 0xFFFF:
        raise ValueError("need 1..65535 filters")
    return bytes([MsgType.GETFILTERS]) + struct.pack(">IH", start_height, count)


def encode_filters(start_height: int, entries: list[tuple[bytes, bytes]]) -> bytes:
    """``entries`` are (block hash, filter bytes) pairs for consecutive
    main-chain heights ascending from ``start_height``."""
    if len(entries) > 0xFFFF:
        raise ValueError("too many filters for one FILTERS frame")
    parts = [
        bytes([MsgType.FILTERS]),
        struct.pack(">IH", start_height, len(entries)),
    ]
    for bhash, fbytes in entries:
        if len(bhash) != 32:
            raise ValueError("block hash must be 32 bytes")
        parts.append(bhash)
        parts.append(_LEN.pack(len(fbytes)))
        parts.append(fbytes)
    return b"".join(parts)


def encode_getfilterheaders(start_height: int, count: int) -> bytes:
    if not 0 <= start_height <= 0xFFFFFFFF:
        raise ValueError("bad filter-header start height")
    if not 0 < count <= 0xFFFF:
        raise ValueError("need 1..65535 filter headers")
    return bytes([MsgType.GETFILTERHEADERS]) + struct.pack(
        ">IH", start_height, count
    )


def encode_filterheaders(start_height: int, headers: list[bytes]) -> bytes:
    """``headers`` are consecutive 32-byte filter-header commitments
    ascending from ``start_height``; an empty list is the clean refusal
    (range not committed here — pruned/re-based history)."""
    if len(headers) > 0xFFFF:
        raise ValueError("too many filter headers for one frame")
    for h in headers:
        if len(h) != 32:
            raise ValueError("filter header must be 32 bytes")
    return (
        bytes([MsgType.FILTERHEADERS])
        + struct.pack(">IH", start_height, len(headers))
        + b"".join(headers)
    )


def encode_subscribe(
    items: list[bytes], cursor: tuple[int, bytes] | None = None
) -> bytes:
    """Register (or replace) the session's watch set.  ``cursor`` is the
    gap-free resume point — the last (height, filter_header) the client
    VERIFIED; the server replays everything after it before pushing
    live, and refuses (drops the session) if its committed chain
    contradicts the cursor — a client would rather fail over than ride
    a server on the wrong branch."""
    if not 0 < len(items) <= 0xFFFF:
        raise ValueError("need 1..65535 watch items")
    if cursor is None:
        head = bytes([MsgType.SUBSCRIBE, 0])
    else:
        height, fheader = cursor
        if len(fheader) != 32:
            raise ValueError("cursor filter header must be 32 bytes")
        head = (
            bytes([MsgType.SUBSCRIBE, 1])
            + struct.pack(">I", height)
            + fheader
        )
    parts = [head, struct.pack(">H", len(items))]
    for it in items:
        if not 0 < len(it) <= 255:
            raise ValueError("watch item must be 1..255 bytes")
        parts.append(bytes([len(it)]))
        parts.append(it)
    return b"".join(parts)


def encode_unsubscribe() -> bytes:
    return bytes([MsgType.UNSUBSCRIBE])


def encode_event(ev: BlockEvent) -> bytes:
    """One block-connect push (EVENT kind 0)."""
    if len(ev.raw_header) != HEADER_SIZE:
        raise ValueError("event header must be exactly 80 bytes")
    if len(ev.filter_header) != 32:
        raise ValueError("event filter header must be 32 bytes")
    if len(ev.txids) > 0xFFFF:
        raise ValueError("too many txids for one EVENT")
    for txid in ev.txids:
        if len(txid) != 32:
            raise ValueError("event txid must be 32 bytes")
    return b"".join(
        (
            bytes([MsgType.EVENT, 0]),
            struct.pack(">I", ev.height),
            ev.raw_header,
            ev.filter_header,
            _LEN.pack(len(ev.filter)),
            ev.filter,
            struct.pack(">BH", int(ev.matched), len(ev.txids)),
            *ev.txids,
        )
    )


def encode_event_gap(start: int, end: int) -> bytes:
    """The drop-to-cursor notice (EVENT kind 1): heights [start, end]
    were shed for this slow consumer instead of queueing unboundedly."""
    if end < start:
        raise ValueError("bad gap range")
    return bytes([MsgType.EVENT, 1]) + struct.pack(">II", start, end)


#: Byte offset of ``tip_height`` inside an encoded found-PROOF payload:
#: type byte + found byte + u32 height puts the u32 tip at bytes 6..10
#: (encode_proof's ">III" pack).  ``patch_proof_tip`` below is what
#: makes serialized proofs cacheable at all: everything else in the
#: payload is reorg-stable (chain/proof.py CachedProof), so serving a
#: cached proof is one 4-byte splice instead of a re-encode.
_PROOF_TIP_OFF = 6


def patch_proof_tip(payload: bytes, tip_height: int) -> bytes:
    """A copy of a cached found-PROOF payload with the current tip height
    stamped in — the hot serving path for repeat proof queries."""
    return (
        payload[:_PROOF_TIP_OFF]
        + struct.pack(">I", tip_height)
        + payload[_PROOF_TIP_OFF + 4 :]
    )


def encode_getsnapshot(start_chunk: int = 0, count: int = 0) -> bytes:
    """``count`` 0 = manifest request; >= 1 = that chunk range."""
    if not 0 <= start_chunk <= 0xFFFFFFFF:
        raise ValueError("bad snapshot start chunk")
    if not 0 <= count <= 0xFFFF:
        raise ValueError("bad snapshot chunk count")
    return bytes([MsgType.GETSNAPSHOT]) + struct.pack(">IH", start_chunk, count)


def encode_snapshot_none() -> bytes:
    return bytes([MsgType.SNAPSHOT, 0])


def encode_snapshot_manifest(manifest_payload: bytes) -> bytes:
    return (
        bytes([MsgType.SNAPSHOT, 1])
        + _LEN.pack(len(manifest_payload))
        + manifest_payload
    )


def encode_snapshot_chunks(start: int, chunk_payloads: list[bytes]) -> bytes:
    if len(chunk_payloads) > 0xFFFF:
        raise ValueError("too many chunks for one SNAPSHOT frame")
    parts = [
        bytes([MsgType.SNAPSHOT, 2]),
        struct.pack(">IH", start, len(chunk_payloads)),
    ]
    for payload in chunk_payloads:
        parts.append(_LEN.pack(len(payload)))
        parts.append(payload)
    return b"".join(parts)


def encode_getproof(txid: bytes) -> bytes:
    if len(txid) != 32:
        raise ValueError("txid must be 32 bytes")
    return bytes([MsgType.GETPROOF]) + txid


def encode_proof(proof: TxProof | None) -> bytes:
    """``None`` encodes the not-found reply."""
    if proof is None:
        return bytes([MsgType.PROOF, 0])
    raw_tx = proof.tx.serialize()
    return b"".join(
        (
            bytes([MsgType.PROOF, 1]),
            struct.pack(">III", proof.height, proof.tip_height, proof.index),
            proof.header.serialize(),
            struct.pack(">H", len(proof.branch)),
            *proof.branch,
            struct.pack(">H", len(raw_tx)),
            raw_tx,
        )
    )


def encode_getmempool(cursor: tuple[int, bytes] | None = None) -> bytes:
    head = bytes([MsgType.GETMEMPOOL])
    if cursor is None:
        return head
    fee, txid = cursor
    return head + struct.pack(">Q32s", fee, txid)


def encode_mempool(raw_txs: list[bytes], more: bool = False) -> bytes:
    """``raw_txs`` are pre-serialized transactions (the caller already
    serialized them for its byte budget — don't pay that twice)."""
    if len(raw_txs) > 0xFFFF:
        raise ValueError("too many transactions for one MEMPOOL frame")
    parts = [
        bytes([MsgType.MEMPOOL]),
        struct.pack(">BH", int(more), len(raw_txs)),
    ]
    for raw in raw_txs:
        parts.append(struct.pack(">H", len(raw)))
        parts.append(raw)
    return b"".join(parts)


#: SKETCH word ceiling: the codec's capacity clamp plus its reserved
#: verification syndrome.  Decoding rejects anything larger OUTRIGHT —
#: an adversarial sketch must not be able to buy unbounded field work.
MAX_SKETCH_WORDS = RECON_MAX_CAPACITY + 1
#: RECONCILDIFF/GETTX short-ID ceiling: a decoded difference can never
#: exceed the capacity clamp, so honest frames stay far below this.
MAX_RECON_IDS = 256


def encode_reqrecon(set_size: int, full: bool = False) -> bytes:
    if not 0 <= set_size <= 0xFFFFFFFF:
        raise ValueError("bad reconciliation set size")
    return bytes([MsgType.REQRECON]) + struct.pack(">BI", int(full), set_size)


def encode_sketch(set_size: int, sketch: bytes) -> bytes:
    """``sketch`` is the serialized codec output (node/reconcile.py) —
    whole 4-byte words, at least capacity 1, at most the clamp."""
    if not 0 <= set_size <= 0xFFFFFFFF:
        raise ValueError("bad reconciliation set size")
    words = len(sketch) // 4
    if len(sketch) % 4 or not 2 <= words <= MAX_SKETCH_WORDS:
        raise ValueError("bad sketch size")
    return (
        bytes([MsgType.SKETCH])
        + struct.pack(">IH", set_size, words)
        + sketch
    )


def _pack_short_ids(short_ids) -> bytes:
    ids = list(short_ids)
    if len(ids) > MAX_RECON_IDS:
        raise ValueError("too many short IDs for one frame")
    if any(not 0 <= s <= 0xFFFFFFFF for s in ids):
        raise ValueError("short ID out of range")
    return struct.pack(">H", len(ids)) + struct.pack(
        f">{len(ids)}I", *ids
    )


def encode_recondiff(success: bool, short_ids=()) -> bytes:
    return (
        bytes([MsgType.RECONCILDIFF, int(success)])
        + _pack_short_ids(short_ids)
    )


def encode_gettx(short_ids) -> bytes:
    ids = list(short_ids)
    if not ids:
        raise ValueError("GETTX needs at least one short ID")
    return bytes([MsgType.GETTX]) + _pack_short_ids(ids)


def decode(payload: bytes):
    """(MsgType, decoded body) for one frame payload; raises
    ``ProtocolError`` (a ValueError) on malformed input — the peer loop
    treats that as a scorable protocol violation."""
    try:
        return _decode(payload)
    except ProtocolError:
        raise
    except ValueError as e:
        # Anything the nested deserializers reject is equally the peer's
        # bytes at fault — normalize so the caller scores uniformly.
        raise ProtocolError(str(e)) from e


def _decode(payload: bytes):
    if not payload:
        raise ValueError("empty frame")
    try:
        mtype = MsgType(payload[0])
    except ValueError as e:
        raise ValueError(f"unknown message type {payload[0]}") from e
    body = payload[1:]
    if mtype is MsgType.HELLO:
        if len(body) != _HELLO.size:
            raise ValueError("bad HELLO size")
        version, *fields = _HELLO.unpack(body)
        if version != PROTOCOL_VERSION:
            raise ChainMismatch(
                f"protocol version mismatch: peer speaks v{version}, "
                f"this node v{PROTOCOL_VERSION}"
            )
        return mtype, Hello(*fields)
    if mtype is MsgType.BLOCK:
        if len(body) < 8:
            raise ValueError("bad BLOCK")
        (sent_ts,) = struct.unpack_from(">d", body)
        return mtype, (sent_ts, Block.deserialize(body[8:]))
    if mtype is MsgType.TX:
        return mtype, Transaction.deserialize(body)
    if mtype is MsgType.GETBLOCKS:
        if len(body) < 2:
            raise ValueError("bad GETBLOCKS")
        (n,) = struct.unpack_from(">H", body)
        if len(body) != 2 + 32 * n:
            raise ValueError("bad GETBLOCKS size")
        return mtype, [body[2 + 32 * i : 2 + 32 * (i + 1)] for i in range(n)]
    if mtype is MsgType.BLOCKS:
        if len(body) < 2:
            raise ValueError("bad BLOCKS")
        (n,) = struct.unpack_from(">H", body)
        off = 2
        blocks = []
        for _ in range(n):
            if len(body) < off + _LEN.size:
                raise ValueError("truncated BLOCKS")
            (blen,) = _LEN.unpack_from(body, off)
            off += _LEN.size
            if len(body) < off + blen:
                raise ValueError("truncated BLOCKS entry")
            blocks.append(Block.deserialize(body[off : off + blen]))
            off += blen
        if off != len(body):
            raise ValueError("trailing bytes in BLOCKS")
        return mtype, blocks
    if mtype is MsgType.GETACCOUNT:
        if len(body) < 1 or len(body) != 1 + body[0] or body[0] == 0:
            raise ValueError("bad GETACCOUNT")
        return mtype, body[1:].decode("utf-8")
    if mtype is MsgType.ACCOUNT:
        if len(body) < 1:
            raise ValueError("bad ACCOUNT")
        alen = body[0]
        if len(body) != 1 + alen + 28 or alen == 0:
            raise ValueError("bad ACCOUNT size")
        account = body[1 : 1 + alen].decode("utf-8")
        balance, nonce, next_seq, height = struct.unpack(
            ">QQQI", body[1 + alen :]
        )
        return mtype, AccountState(account, balance, nonce, next_seq, height)
    if mtype is MsgType.CBLOCK:
        if len(body) < 8 + HEADER_SIZE + 4:
            raise ValueError("bad CBLOCK")
        (sent_ts,) = struct.unpack_from(">d", body)
        off = 8
        header = BlockHeader.deserialize(body[off : off + HEADER_SIZE])
        off += HEADER_SIZE
        ntx, n_prefilled = struct.unpack_from(">HH", body, off)
        off += 4
        if n_prefilled > ntx:
            raise ValueError("bad CBLOCK prefill count")
        prefilled = []
        last_index = -1
        for _ in range(n_prefilled):
            if len(body) < off + 6:
                raise ValueError("truncated CBLOCK prefill")
            index, tlen = struct.unpack_from(">HI", body, off)
            off += 6
            if index <= last_index or index >= ntx:
                raise ValueError("bad CBLOCK prefill index")
            last_index = index
            if len(body) < off + tlen:
                raise ValueError("truncated CBLOCK prefill tx")
            prefilled.append(
                (index, Transaction.deserialize(body[off : off + tlen]))
            )
            off += tlen
        n_ids = ntx - n_prefilled
        if len(body) != off + 32 * n_ids:
            raise ValueError("bad CBLOCK txid section")
        txids = tuple(
            body[off + 32 * i : off + 32 * (i + 1)] for i in range(n_ids)
        )
        return mtype, CompactBlock(
            sent_ts, header, ntx, tuple(prefilled), txids
        )
    if mtype is MsgType.GETBLOCKTXN:
        if len(body) < 34:
            raise ValueError("bad GETBLOCKTXN")
        bhash = body[:32]
        (n,) = struct.unpack_from(">H", body, 32)
        if n == 0 or len(body) != 34 + 2 * n:
            raise ValueError("bad GETBLOCKTXN size")
        indices = list(struct.unpack_from(f">{n}H", body, 34))
        if any(b <= a for a, b in zip(indices, indices[1:])):
            raise ValueError("GETBLOCKTXN indices must ascend")
        return mtype, (bhash, indices)
    if mtype is MsgType.BLOCKTXN:
        if len(body) < 34:
            raise ValueError("bad BLOCKTXN")
        bhash = body[:32]
        (n,) = struct.unpack_from(">H", body, 32)
        off = 34
        txs = []
        for _ in range(n):
            if len(body) < off + 4:
                raise ValueError("truncated BLOCKTXN")
            (tlen,) = struct.unpack_from(">I", body, off)
            off += 4
            if len(body) < off + tlen:
                raise ValueError("truncated BLOCKTXN entry")
            txs.append(Transaction.deserialize(body[off : off + tlen]))
            off += tlen
        if off != len(body):
            raise ValueError("trailing bytes in BLOCKTXN")
        return mtype, (bhash, txs)
    if mtype is MsgType.GETFEES:
        if len(body) != 2:
            raise ValueError("bad GETFEES")
        return mtype, struct.unpack(">H", body)[0]
    if mtype is MsgType.FEES:
        if len(body) != 34:
            raise ValueError("bad FEES")
        return mtype, FeeStats(*struct.unpack(">HIQQQI", body))
    if mtype is MsgType.GETADDR:
        if body:
            raise ValueError("bad GETADDR")
        return mtype, None
    if mtype is MsgType.GETSTATUS:
        if body:
            raise ValueError("bad GETSTATUS")
        return mtype, None
    if mtype is MsgType.GETMETRICS:
        if body:
            raise ValueError("bad GETMETRICS")
        return mtype, None
    if mtype in (MsgType.STATUS, MsgType.METRICS, MsgType.GETMAINTAIN, MsgType.MAINTAIN):
        import json

        try:
            status = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise ValueError(f"bad {mtype.name} payload: {e}") from e
        if not isinstance(status, dict):
            raise ValueError(f"bad {mtype.name} payload: not an object")
        return mtype, status
    if mtype in (MsgType.PING, MsgType.PONG):
        if len(body) != 8:
            raise ValueError(f"bad {mtype.name}")
        return mtype, struct.unpack(">Q", body)[0]
    if mtype is MsgType.ADDR:
        if len(body) < 2:
            raise ValueError("bad ADDR")
        (n,) = struct.unpack_from(">H", body)
        off = 2
        addrs = []
        for _ in range(n):
            if len(body) < off + 3:
                raise ValueError("truncated ADDR")
            port, hlen = struct.unpack_from(">HB", body, off)
            off += 3
            if hlen == 0 or port == 0 or len(body) < off + hlen:
                raise ValueError("bad ADDR entry")
            addrs.append((body[off : off + hlen].decode("utf-8"), port))
            off += hlen
        if off != len(body):
            raise ValueError("trailing bytes in ADDR")
        return mtype, addrs
    if mtype is MsgType.GETHEADERS:
        if len(body) < 2:
            raise ValueError("bad GETHEADERS")
        (n,) = struct.unpack_from(">H", body)
        if len(body) != 2 + 32 * n:
            raise ValueError("bad GETHEADERS size")
        return mtype, [body[2 + 32 * i : 2 + 32 * (i + 1)] for i in range(n)]
    if mtype is MsgType.HEADERS:
        if len(body) < 2:
            raise ValueError("bad HEADERS")
        (n,) = struct.unpack_from(">H", body)
        if len(body) != 2 + HEADER_SIZE * n:
            raise ValueError("bad HEADERS size")
        return mtype, [
            BlockHeader.deserialize(
                body[2 + HEADER_SIZE * i : 2 + HEADER_SIZE * (i + 1)]
            )
            for i in range(n)
        ]
    if mtype is MsgType.GETFILTERS:
        if len(body) != 6:
            raise ValueError("bad GETFILTERS")
        start, count = struct.unpack(">IH", body)
        if count == 0:
            raise ValueError("bad GETFILTERS count")
        return mtype, (start, count)
    if mtype is MsgType.FILTERS:
        if len(body) < 6:
            raise ValueError("bad FILTERS")
        start, n = struct.unpack_from(">IH", body)
        off = 6
        entries = []
        for _ in range(n):
            if len(body) < off + 36:
                raise ValueError("truncated FILTERS")
            bhash = body[off : off + 32]
            (flen,) = _LEN.unpack_from(body, off + 32)
            off += 36
            if len(body) < off + flen:
                raise ValueError("truncated FILTERS entry")
            entries.append((bhash, body[off : off + flen]))
            off += flen
        if off != len(body):
            raise ValueError("trailing bytes in FILTERS")
        return mtype, (start, entries)
    if mtype is MsgType.GETFILTERHEADERS:
        if len(body) != 6:
            raise ValueError("bad GETFILTERHEADERS")
        start, count = struct.unpack(">IH", body)
        if count == 0:
            raise ValueError("bad GETFILTERHEADERS count")
        return mtype, (start, count)
    if mtype is MsgType.FILTERHEADERS:
        if len(body) < 6:
            raise ValueError("bad FILTERHEADERS")
        start, n = struct.unpack_from(">IH", body)
        if len(body) != 6 + 32 * n:
            raise ValueError("bad FILTERHEADERS size")
        return mtype, (
            start,
            [body[6 + 32 * i : 6 + 32 * (i + 1)] for i in range(n)],
        )
    if mtype is MsgType.SUBSCRIBE:
        if len(body) < 1:
            raise ValueError("bad SUBSCRIBE")
        has_cursor = body[0]
        if has_cursor not in (0, 1):
            raise ValueError("bad SUBSCRIBE cursor flag")
        off = 1
        cursor = None
        if has_cursor:
            if len(body) < off + 36:
                raise ValueError("truncated SUBSCRIBE cursor")
            (height,) = struct.unpack_from(">I", body, off)
            cursor = (height, body[off + 4 : off + 36])
            off += 36
        if len(body) < off + 2:
            raise ValueError("truncated SUBSCRIBE")
        (n,) = struct.unpack_from(">H", body, off)
        off += 2
        if n == 0:
            raise ValueError("SUBSCRIBE needs at least one watch item")
        items = []
        for _ in range(n):
            if len(body) < off + 1:
                raise ValueError("truncated SUBSCRIBE item")
            ilen = body[off]
            off += 1
            if ilen == 0 or len(body) < off + ilen:
                raise ValueError("bad SUBSCRIBE item")
            items.append(body[off : off + ilen])
            off += ilen
        if off != len(body):
            raise ValueError("trailing bytes in SUBSCRIBE")
        return mtype, (cursor, items)
    if mtype is MsgType.UNSUBSCRIBE:
        if body:
            raise ValueError("bad UNSUBSCRIBE")
        return mtype, None
    if mtype is MsgType.EVENT:
        if len(body) < 1:
            raise ValueError("bad EVENT")
        kind = body[0]
        if kind == 1:
            if len(body) != 9:
                raise ValueError("bad EVENT gap size")
            start, end = struct.unpack_from(">II", body, 1)
            if end < start:
                raise ValueError("bad EVENT gap range")
            return mtype, GapEvent(start, end)
        if kind != 0:
            raise ValueError(f"bad EVENT kind {kind}")
        off = 1
        if len(body) < off + 4 + HEADER_SIZE + 32 + _LEN.size:
            raise ValueError("truncated EVENT")
        (height,) = struct.unpack_from(">I", body, off)
        off += 4
        raw_header = body[off : off + HEADER_SIZE]
        off += HEADER_SIZE
        fheader = body[off : off + 32]
        off += 32
        (flen,) = _LEN.unpack_from(body, off)
        off += _LEN.size
        if len(body) < off + flen + 3:
            raise ValueError("truncated EVENT filter")
        fbytes = body[off : off + flen]
        off += flen
        matched, ntx = struct.unpack_from(">BH", body, off)
        off += 3
        if matched not in (0, 1):
            raise ValueError("bad EVENT matched flag")
        if len(body) != off + 32 * ntx:
            raise ValueError("bad EVENT txid section")
        txids = tuple(
            body[off + 32 * i : off + 32 * (i + 1)] for i in range(ntx)
        )
        return mtype, BlockEvent(
            height, raw_header, fheader, fbytes, bool(matched), txids
        )
    if mtype is MsgType.GETSNAPSHOT:
        if len(body) != 6:
            raise ValueError("bad GETSNAPSHOT")
        return mtype, struct.unpack(">IH", body)
    if mtype is MsgType.SNAPSHOT:
        if len(body) < 1:
            raise ValueError("bad SNAPSHOT")
        kind = body[0]
        if kind == 0:
            if len(body) != 1:
                raise ValueError("trailing bytes in SNAPSHOT")
            return mtype, ("none",)
        if kind == 1:
            if len(body) < 1 + _LEN.size:
                raise ValueError("truncated SNAPSHOT manifest")
            (mlen,) = _LEN.unpack_from(body, 1)
            if len(body) != 1 + _LEN.size + mlen:
                raise ValueError("bad SNAPSHOT manifest length")
            return mtype, ("manifest", body[1 + _LEN.size :])
        if kind == 2:
            if len(body) < 7:
                raise ValueError("truncated SNAPSHOT chunks")
            start, n = struct.unpack_from(">IH", body, 1)
            off = 7
            chunks = []
            for _ in range(n):
                if len(body) < off + _LEN.size:
                    raise ValueError("truncated SNAPSHOT chunk")
                (clen,) = _LEN.unpack_from(body, off)
                off += _LEN.size
                if len(body) < off + clen:
                    raise ValueError("truncated SNAPSHOT chunk entry")
                chunks.append(body[off : off + clen])
                off += clen
            if off != len(body):
                raise ValueError("trailing bytes in SNAPSHOT")
            return mtype, ("chunks", start, chunks)
        raise ValueError(f"bad SNAPSHOT kind {kind}")
    if mtype is MsgType.GETPROOF:
        if len(body) != 32:
            raise ValueError("bad GETPROOF")
        return mtype, body
    if mtype is MsgType.PROOF:
        if len(body) < 1:
            raise ValueError("bad PROOF")
        if body[0] == 0:
            if len(body) != 1:
                raise ValueError("trailing bytes in PROOF")
            return mtype, None
        if body[0] != 1:
            raise ValueError("bad PROOF found flag")
        off = 1
        if len(body) < off + 12 + HEADER_SIZE + 2:
            raise ValueError("truncated PROOF")
        height, tip_height, index = struct.unpack_from(">III", body, off)
        off += 12
        header = BlockHeader.deserialize(body[off : off + HEADER_SIZE])
        off += HEADER_SIZE
        (nbranch,) = struct.unpack_from(">H", body, off)
        off += 2
        if len(body) < off + 32 * nbranch + 2:
            raise ValueError("truncated PROOF branch")
        branch = tuple(
            body[off + 32 * i : off + 32 * (i + 1)] for i in range(nbranch)
        )
        off += 32 * nbranch
        (txlen,) = struct.unpack_from(">H", body, off)
        off += 2
        if len(body) != off + txlen:
            raise ValueError("bad PROOF tx size")
        tx = Transaction.deserialize(body[off:])
        return mtype, TxProof(tx, header, height, tip_height, index, branch)
    if mtype is MsgType.GETMEMPOOL:
        if not body:
            return mtype, None
        if len(body) != 40:
            raise ValueError("bad GETMEMPOOL")
        fee, txid = struct.unpack(">Q32s", body)
        return mtype, (fee, txid)
    if mtype is MsgType.MEMPOOL:
        if len(body) < 3:
            raise ValueError("bad MEMPOOL")
        more, n = struct.unpack_from(">BH", body)
        off = 3
        txs = []
        for _ in range(n):
            if len(body) < off + 2:
                raise ValueError("truncated MEMPOOL")
            (tlen,) = struct.unpack_from(">H", body, off)
            off += 2
            if len(body) < off + tlen:
                raise ValueError("truncated MEMPOOL entry")
            txs.append(Transaction.deserialize(body[off : off + tlen]))
            off += tlen
        if off != len(body):
            raise ValueError("trailing bytes in MEMPOOL")
        return mtype, (bool(more), txs)
    if mtype is MsgType.REQRECON:
        if len(body) != 5:
            raise ValueError("bad REQRECON")
        full, set_size = struct.unpack(">BI", body)
        if full > 1:
            raise ValueError("bad REQRECON full flag")
        return mtype, (bool(full), set_size)
    if mtype is MsgType.SKETCH:
        if len(body) < 6:
            raise ValueError("bad SKETCH")
        set_size, words = struct.unpack_from(">IH", body)
        if not 2 <= words <= MAX_SKETCH_WORDS:
            raise ValueError("bad SKETCH word count")
        if len(body) != 6 + 4 * words:
            raise ValueError("bad SKETCH size")
        return mtype, (set_size, body[6:])
    if mtype is MsgType.RECONCILDIFF:
        if len(body) < 3:
            raise ValueError("bad RECONCILDIFF")
        success = body[0]
        if success > 1:
            raise ValueError("bad RECONCILDIFF flag")
        return mtype, (bool(success), _unpack_short_ids(body[1:]))
    if mtype is MsgType.GETTX:
        ids = _unpack_short_ids(body)
        if not ids:
            raise ValueError("empty GETTX")
        return mtype, ids
    raise AssertionError(mtype)


def _unpack_short_ids(body: bytes) -> tuple:
    if len(body) < 2:
        raise ValueError("bad short-ID list")
    (n,) = struct.unpack_from(">H", body)
    if n > MAX_RECON_IDS:
        raise ValueError("too many short IDs")
    if len(body) != 2 + 4 * n:
        raise ValueError("bad short-ID list size")
    return struct.unpack_from(f">{n}I", body, 2)


async def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)) + payload)
    await writer.drain()


def write_frame_nowait(writer, payload: bytes) -> None:
    """Buffer one frame without draining — the push plane's send
    primitive.  A slow consumer grows the transport write buffer
    instead of blocking the notifier; the subscription ladder
    (node/subscriptions.py) reads that buffer size and degrades
    (coalesce → drop-to-cursor → disconnect) long before the hard cap.
    drain() here would invert that: one stalled wallet at the default
    64 KiB high-water mark would block every other subscriber's
    notification."""
    writer.write(_LEN.pack(len(payload)) + payload)


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        # Purely peer-supplied bytes: a hostile length prefix is the
        # canonical scorable violation (node misbehavior bans).
        raise ProtocolError(f"frame of {n} bytes exceeds cap")
    return await reader.readexactly(n)


#: Minimum sustained delivery rate for an in-progress frame.  Byte-level
#: progress counts as liveness (a slow link mid-frame is not silence) —
#: but unboundedly so, one byte per probe interval would re-pin a peer
#: slot forever, the very attack the liveness layer closes.  So a frame
#: must complete within grace + promised_size / MIN_FRAME_RATE seconds
#: (``FrameReader.overdue``; node.py passes its idle deadlines as the
#: grace).  10 kB/s tolerates any link worth keeping, and even a hostile
#: 32 MB frame trickled at exactly the floor bounds the slot hold to
#: under an hour of real bandwidth spent — paid, not free.
MIN_FRAME_RATE = 10_000


class FrameReader:
    """Cancellation-tolerant framed reader for the node's session loop.

    ``read_frame`` is two ``readexactly`` calls; a timeout (``wait_for``)
    that cancels it BETWEEN them — length prefix consumed, body pending —
    desyncs the stream, and the next read then interprets body bytes as a
    length prefix: an honest-but-slow peer would be scored for a protocol
    violation it never committed.  This reader instead accumulates all
    partial progress in its own buffer, so a cancelled ``read`` resumes at
    the exact stream position, and it records whether ANY bytes arrived
    since the last completed frame (``progressed``) — the idle prober's
    way to tell a peer trickling a large frame over a slow link (alive,
    never evicted while ``overdue`` is not) from one that has gone silent.
    """

    def __init__(self, reader: asyncio.StreamReader, clock=time.monotonic):
        self._reader = reader
        self._buf = bytearray()
        self._need: int | None = None  # body length once the prefix parsed
        self._progress = False
        self._started: float | None = None  # first byte of current frame
        #: Injectable monotonic clock (tests drive the delivery-budget
        #: math without real sleeps — the round-9 liveness deflake; the
        #: governor's TokenBucket set the pattern).
        self._clock = clock

    def progressed(self) -> bool:
        """True if bytes arrived mid-frame since the last completed frame
        or last call (the flag is consumed)."""
        p = self._progress
        self._progress = False
        return p

    def overdue(self, grace: float) -> bool:
        """True when the in-progress frame has outlived its delivery
        budget of ``grace`` + promised_size / MIN_FRAME_RATE seconds —
        the bound that keeps byte-trickle liveness from being free."""
        if self._started is None:
            return False
        budget = grace + (self._need or _LEN.size) / MIN_FRAME_RATE
        return self._clock() - self._started > budget

    async def read(self) -> bytes:
        while True:
            target = _LEN.size if self._need is None else self._need
            while len(self._buf) < target:
                chunk = await self._reader.read(target - len(self._buf))
                if not chunk:
                    raise asyncio.IncompleteReadError(bytes(self._buf), target)
                if self._started is None:
                    self._started = self._clock()
                self._progress = True
                self._buf += chunk
            if self._need is None:
                (n,) = _LEN.unpack(bytes(self._buf))
                if n > MAX_FRAME:
                    raise ProtocolError(f"frame of {n} bytes exceeds cap")
                self._need = n
                self._buf.clear()
                continue
            payload = bytes(self._buf)
            self._buf.clear()
            self._need = None
            self._started = None
            # A completed frame is reported to the caller, which resets its
            # idle state wholesale; ``progressed`` is reserved for partial
            # progress only, so one finished frame can't also grant a later
            # silent interval a free pass.
            self._progress = False
            return payload
