"""The sharded far-field plane: 10,000 simulated nodes on one host.

The full-fidelity simulator (node/netsim.py) runs REAL ``Node``
instances — chain, mempool, governor, supervision — which is exactly
why it tops out around a thousand nodes per process: every node costs
an asyncio task set, a chain index, and a governor.  Real networks have
10k+ participants, but the far field of a gossip mesh is mostly
*header relays*: nodes that receive announcements, deduplicate, follow
the heaviest tip, and forward.  This module models that far field
honestly as what it is — a header-only node (tip + seen-set + orphan
buffer + relay) — and makes the resulting discrete-event simulation
**shardable across processes** with deterministic cross-shard event
exchange, so the 10k-node scenario in node/scenarios.py fits one host
in tier-1-adjacent wall time.

Design, in three layers:

- **Pure-function world.**  Topology (``topology``) and per-direction
  link latency (``link_latency``) are pure functions of ``(seed, node
  ids)`` via SHA-256 draws — no shared RNG stream whose draw ORDER
  could differ between shard layouts.  Time is integer microseconds
  end to end: float arithmetic never enters the event path, so two
  runs (or two shard layouts) can be compared byte-for-byte.

- **Conservative virtual-time barriers.**  Every latency is at least
  ``LAT_MIN_US``, so an event processed at time ``t`` can only
  schedule effects at ``t + LAT_MIN_US`` or later.  The coordinator
  repeatedly (1) finds the globally earliest pending event time ``m``,
  (2) lets every shard process its local events with ``t < m +
  LAT_MIN_US`` — nothing another shard does this round can land inside
  that window — and (3) routes the cross-shard sends for the next
  round.  Idle virtual time is skipped entirely (the bound chases the
  next event, it does not tick), which is what makes multi-minute
  virtual horizons cost milliseconds.

- **One merged trace.**  Each shard processes its heap in full event
  order ``(t_us, dst, src, height, block id)``, so its per-round
  delivery list is sorted; the coordinator merge-sorts the shards'
  lists and feeds ONE running SHA-256.  Rounds never overlap in time
  (window k+1 starts at window k's bound), so the merged stream is the
  total event order regardless of the shard count: **same seed ⇒ the
  same digest at 1 shard and at N shards, in one process or across
  processes** — the contract tests/test_farfield.py and the `p1 sim
  far-field --shards` CLI pair assert, PYTHONHASHSEED pinned, exactly
  like the PR 7/8 determinism pairs.

Cross-process shards are ``multiprocessing`` workers over pipes (the
spawn context: a clean interpreter per shard, nothing inherited but
the arguments), driven by the same coordinator loop as the in-process
mode; the pipe protocol is one request/response per barrier round.
All of it is ordinary synchronous code — the shard exchange never runs
on an asyncio loop, so the blocking pipe reads need no
transitive-blocking grant in p1_tpu/analysis/allowlist.py, and must
not grow one by moving onto a loop.

What the far-field model does NOT capture (honesty — docs/PERF.md
"Sharded far field" repeats this next to the numbers): no transaction
traffic, mempools, ledgers, or stores (headers only); no bandwidth
shaping, handshakes, supervision, or admission control (a far-field
node never stalls, floods, or gets banned); relay is announce-forward
with per-link latency only; and the coupling to the full-node core is
ONE-WAY — far-field demand never back-pressures the core mesh.  Any
result that depends on those belongs in the full simulator.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import time

__all__ = [
    "FarFieldReport",
    "FarShard",
    "LAT_MIN_US",
    "LAT_MAX_US",
    "link_latency_us",
    "run_far_field",
    "shard_bounds",
    "topology",
]

#: Per-direction link latency band, integer microseconds.  The floor is
#: the barrier window (the lookahead every conservative parallel
#: discrete-event scheme needs); the ceiling keeps the band WAN-shaped.
LAT_MIN_US = 10_000  # 10 ms
LAT_MAX_US = 250_000  # 250 ms

#: Entry points where core-mesh announcements reach the far field.
GATEWAYS = 8


def _draw(seed: int, *fields: int) -> int:
    """One deterministic 64-bit draw: a pure function of its arguments
    (no stream, no order dependence — any shard can evaluate any draw)."""
    h = hashlib.sha256()
    h.update(b"farfield")
    for f in (seed, *fields):
        h.update(int(f).to_bytes(16, "little", signed=True))
    return int.from_bytes(h.digest()[:8], "little")


def link_latency_us(seed: int, src: int, dst: int) -> int:
    """Directional latency for src→dst, in [LAT_MIN_US, LAT_MAX_US)."""
    span = LAT_MAX_US - LAT_MIN_US
    return LAT_MIN_US + _draw(seed, 1, src, dst) % span


def topology(seed: int, n: int, degree: int = 4) -> list[list[int]]:
    """Symmetric adjacency: node i always links i-1 (a backbone, so the
    graph is connected by construction) plus ``degree - 1`` pure-draw
    earlier nodes — the same backbone+small-world shape the full-node
    scenarios use (scenarios._topology_peers), as a pure function."""
    adj: list[list[int]] = [[] for _ in range(n)]
    for i in range(1, n):
        peers = {i - 1}
        for k in range(degree - 1):
            if i >= 2:
                peers.add(_draw(seed, 2, i, k) % (i - 1))
        for j in sorted(peers):
            adj[i].append(j)
            adj[j].append(i)
    return adj


def shard_bounds(n: int, shards: int) -> list[tuple[int, int]]:
    """Contiguous [lo, hi) node ranges, one per shard."""
    assert 1 <= shards <= n, (n, shards)
    out = []
    base, rem = divmod(n, shards)
    lo = 0
    for s in range(shards):
        hi = lo + base + (1 if s < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


class FarShard:
    """One shard's worth of header-only nodes and their event heap.

    Event tuples are ``(t_us, dst, src, height, bid)`` — the heap order
    IS the trace order, so ``process()`` returns its deliveries already
    sorted.  ``src == -1`` marks a gateway injection from the core mesh.
    """

    def __init__(self, seed: int, n: int, lo: int, hi: int, degree: int):
        self.seed = seed
        self.n = n
        self.lo = lo
        self.hi = hi
        self.adj = topology(seed, n, degree)
        self.heap: list[tuple] = []
        #: nid -> {bid: (height, parent)} — headers this node accepted.
        self.have: dict[int, dict[str, tuple[int, str]]] = {}
        #: nid -> (height, bid) best tip (first-seen wins height ties).
        self.tips: dict[int, tuple[int, str]] = {}
        #: nid -> {parent_bid: [(height, bid)]} — parked until linkable.
        self.orphans: dict[int, dict[str, list[tuple[int, str]]]] = {}
        #: (nid, bid) -> first-arrival t_us, for propagation figures.
        self.arrivals: dict[tuple[int, str], int] = {}
        self.deliveries = 0

    def push(self, ev: tuple) -> None:
        heapq.heappush(self.heap, ev)

    def next_time(self) -> int | None:
        return self.heap[0][0] if self.heap else None

    def _accept(
        self, nid: int, height: int, bid: str, parent: str, sends: list
    ) -> None:
        """Header connects: record it, move the tip if it wins, relay to
        every neighbor, then un-park any orphan children."""
        have = self.have.setdefault(nid, {})
        have[bid] = (height, parent)
        tip = self.tips.get(nid)
        if tip is None or height > tip[0]:
            self.tips[nid] = (height, bid)
        for nbr in self.adj[nid]:
            sends.append(
                (
                    self._now + link_latency_us(self.seed, nid, nbr),
                    nbr,
                    nid,
                    height,
                    bid,
                )
            )
        parked = self.orphans.get(nid)
        if parked is not None:
            children = parked.pop(bid, ())
            if not parked:
                # Drop the empty per-node buffer BEFORE recursing: the
                # recursive accept may empty-and-delete it again.
                self.orphans.pop(nid, None)
            for oh, obid in children:
                self._accept(nid, oh, obid, bid, sends)

    def process(self, bound_us: int, feed: dict) -> tuple[list, list]:
        """Run every local event with ``t < bound_us``.  Returns
        ``(cross_shard_sends, deliveries)`` — deliveries in heap (trace)
        order, cross sends as raw event tuples for the coordinator to
        route.  ``feed`` maps bid -> (height, parent) for header lookup
        on gateway injections (relays carry it per event already)."""
        cross: list[tuple] = []
        deliveries: list[tuple] = []
        heap = self.heap
        while heap and heap[0][0] < bound_us:
            ev = heapq.heappop(heap)
            t_us, dst, src, height, bid = ev
            self._now = t_us
            deliveries.append(ev)
            self.deliveries += 1
            have = self.have.setdefault(dst, {})
            if bid in have:
                continue  # duplicate announcement: dedup, no relay
            key = (dst, bid)
            if key not in self.arrivals:
                self.arrivals[key] = t_us
            parent = feed[bid][1]
            sends: list[tuple] = []
            if parent == "" or parent in have:
                self._accept(dst, height, bid, parent, sends)
            else:
                self.orphans.setdefault(dst, {}).setdefault(
                    parent, []
                ).append((height, bid))
            for s in sends:
                if self.lo <= s[1] < self.hi:
                    heapq.heappush(heap, s)
                else:
                    cross.append(s)
        return cross, deliveries


# -- cross-process worker --------------------------------------------------


def _shard_worker(conn, seed: int, n: int, lo: int, hi: int, degree: int,
                  feed: dict) -> None:
    """One shard in its own process: answer barrier-round requests over
    the pipe until told to stop.  Protocol (coordinator side is
    ``_ProcShard``): recv ``("step", bound, in_events)`` → process →
    send ``(next_time, cross_sends, deliveries)``; recv ``("done",)`` →
    send final per-shard state and exit."""
    shard = FarShard(seed, n, lo, hi, degree)
    while True:
        msg = conn.recv()
        if msg[0] == "step":
            _, bound, in_events = msg
            for ev in in_events:
                shard.push(ev)
            cross, deliveries = shard.process(bound, feed)
            conn.send((shard.next_time(), cross, deliveries))
        elif msg[0] == "done":
            conn.send((shard.tips, shard.arrivals, shard.deliveries))
            conn.close()
            return


class _ProcShard:
    """Coordinator-side handle speaking the worker protocol."""

    def __init__(self, ctx, seed, n, lo, hi, degree, feed):
        self.lo, self.hi = lo, hi
        self.conn, child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_shard_worker,
            args=(child, seed, n, lo, hi, degree, feed),
            daemon=True,
        )
        self.proc.start()
        child.close()
        self._pending_in: list[tuple] = []
        self._next: int | None = None

    def push(self, ev: tuple) -> None:
        self._pending_in.append(ev)
        if self._next is None or ev[0] < self._next:
            self._next = ev[0]

    def next_time(self) -> int | None:
        return self._next

    def step(self, bound: int) -> None:
        self.conn.send(("step", bound, self._pending_in))
        self._pending_in = []

    def result(self) -> tuple:
        nxt, cross, deliveries = self.conn.recv()
        self._next = nxt
        return cross, deliveries

    def finish(self) -> tuple:
        self.conn.send(("done",))
        tips, arrivals, deliveries = self.conn.recv()
        self.conn.close()
        self.proc.join(timeout=30)
        return tips, arrivals, deliveries

    def kill(self) -> None:
        """Error-path teardown: a coordinator abort must not strand
        worker processes behind it."""
        try:
            self.conn.close()
        except OSError:
            pass
        if self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(timeout=5)


class _LocalShard:
    """In-process shard with the same coordinator surface."""

    def __init__(self, seed, n, lo, hi, degree, feed):
        self.lo, self.hi = lo, hi
        self._feed = feed
        self._shard = FarShard(seed, n, lo, hi, degree)
        self._result: tuple | None = None

    def push(self, ev: tuple) -> None:
        self._shard.push(ev)

    def next_time(self) -> int | None:
        nxt = self._shard.next_time()
        return nxt

    def step(self, bound: int) -> None:
        self._result = self._shard.process(bound, self._feed)

    def result(self) -> tuple:
        r, self._result = self._result, None
        return r

    def finish(self) -> tuple:
        s = self._shard
        return s.tips, s.arrivals, s.deliveries


@dataclasses.dataclass
class FarFieldReport:
    """What one far-field run measured (node/scenarios.py folds this
    into the scenario report)."""

    nodes: int
    shards: int
    processes: bool
    deliveries: int
    rounds: int
    converged_nodes: int
    converged: bool
    final_tip: tuple[int, str]
    #: Last header arrival, µs after its injection — the far field's
    #: convergence lag behind the core mesh.
    settle_ms: float
    #: Per-block propagation percentiles (injection → first arrival),
    #: virtual ms, across all nodes and blocks.
    propagation_p50_ms: float
    propagation_p95_ms: float
    wall_s: float
    trace_digest: str


def run_far_field(
    nodes: int,
    seed: int,
    feed: list[tuple[float, int, str, str]],
    degree: int = 4,
    shards: int = 1,
    processes: bool | None = None,
    wall_limit_s: float | None = 300.0,
) -> FarFieldReport:
    """Run one far-field simulation to quiescence.

    ``feed`` is the core mesh's announcement schedule: ``(t_s, height,
    bid, parent_bid)`` per block, virtual seconds (parent "" = the
    far field's genesis anchor — accepted linklessly).  ``shards`` > 1
    with ``processes`` unset (or True) runs one OS process per shard
    over the pipe seam; ``processes=False`` keeps the same sharded
    exchange in-process (the fast path for determinism pairs).
    """
    assert nodes >= 1 and shards >= 1
    if processes is None:
        processes = shards > 1
    t0 = time.monotonic()
    feed_map = {bid: (height, parent) for _t, height, bid, parent in feed}
    bounds = shard_bounds(nodes, shards)

    if processes and shards > 1:
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        shard_objs: list = [
            _ProcShard(ctx, seed, nodes, lo, hi, degree, feed_map)
            for lo, hi in bounds
        ]
    else:
        shard_objs = [
            _LocalShard(seed, nodes, lo, hi, degree, feed_map)
            for lo, hi in bounds
        ]

    def owner(nid: int):
        for so in shard_objs:
            if so.lo <= nid < so.hi:
                return so
        raise AssertionError(nid)

    # Gateway injections: each announcement enters at GATEWAYS evenly
    # spaced far-field nodes, after a per-gateway pure-draw latency
    # (the gateway's path from the core mesh).
    n_gw = max(1, min(GATEWAYS, nodes))
    gateways = [g * nodes // n_gw for g in range(n_gw)]
    inject_us: dict[str, int] = {}
    for t_s, height, bid, _parent in feed:
        t_us = round(t_s * 1e6)
        inject_us[bid] = t_us
        for g, gw in enumerate(gateways):
            lat = link_latency_us(seed, -1 - g, gw)
            owner(gw).push((t_us + lat, gw, -1, height, bid))

    hasher = hashlib.sha256()
    deliveries_total = 0
    rounds = 0
    try:
        deliveries_total, rounds = _drive(
            shard_objs, owner, hasher, t0, wall_limit_s
        )
    except BaseException:
        for so in shard_objs:
            if isinstance(so, _ProcShard):
                so.kill()
        raise

    # Quiesce: collect per-shard end state (and reap workers).
    tips: dict[int, tuple[int, str]] = {}
    arrivals: dict[tuple[int, str], int] = {}
    for so in shard_objs:
        s_tips, s_arrivals, _n = so.finish()
        tips.update(s_tips)
        arrivals.update(s_arrivals)

    final_tip = max(
        ((h, bid) for _t, h, bid, _p in feed), default=(0, "")
    )
    converged_nodes = sum(
        1 for nid in range(nodes) if tips.get(nid) == final_tip
    )
    delays_ms = sorted(
        (t_us - inject_us[bid]) / 1e3
        for (_nid, bid), t_us in arrivals.items()
    )

    def pct(p: float) -> float:
        if not delays_ms:
            return 0.0
        return delays_ms[min(len(delays_ms) - 1, int(p * len(delays_ms)))]

    settle_ms = delays_ms[-1] if delays_ms else 0.0
    return FarFieldReport(
        nodes=nodes,
        shards=shards,
        processes=bool(processes and shards > 1),
        deliveries=deliveries_total,
        rounds=rounds,
        converged_nodes=converged_nodes,
        converged=converged_nodes == nodes,
        final_tip=final_tip,
        settle_ms=round(settle_ms, 3),
        propagation_p50_ms=round(pct(0.50), 3),
        propagation_p95_ms=round(pct(0.95), 3),
        wall_s=round(time.monotonic() - t0, 3),
        trace_digest=hasher.hexdigest(),
    )


def _drive(shard_objs, owner, hasher, t0, wall_limit_s) -> tuple[int, int]:
    """The barrier loop (module docstring): rounds of find-min →
    process-window → merge-trace → route-cross, until global quiesce."""
    deliveries_total = 0
    rounds = 0
    while True:
        nexts = [so.next_time() for so in shard_objs]
        live = [x for x in nexts if x is not None]
        if not live:
            break
        if (
            wall_limit_s is not None
            and time.monotonic() - t0 > wall_limit_s
        ):
            raise RuntimeError(
                f"far-field run burned {wall_limit_s:.0f}s of wall time "
                f"after {rounds} barrier rounds"
            )
        bound = min(live) + LAT_MIN_US
        for so in shard_objs:
            so.step(bound)
        round_streams = []
        cross_all: list[tuple] = []
        for so in shard_objs:
            cross, deliveries = so.result()
            cross_all.extend(cross)
            round_streams.append(deliveries)
        for ev in heapq.merge(*round_streams):
            hasher.update(repr(ev).encode())
            deliveries_total += 1
        for ev in sorted(cross_all):
            owner(ev[1]).push(ev)
        rounds += 1
    return deliveries_total, rounds
