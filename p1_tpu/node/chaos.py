"""The chaos plane: combined-fault search over the simulated mesh.

Every robustness layer so far was proven against ONE fault family at a
time — supervision against stalling peers (round 6), the FaultStore
against a bad disk (round 7), the governor against floods (round 8),
the simulator against partitions (round 10).  Jepsen-style experience
says the bugs that split chains live in the *compositions*: a crash
during a reorg while the disk is ENOSPC-degraded and the mesh is
partitioned.  This module points the deterministic simulator
(node/netsim.py) at exactly that space:

- ``generate_schedule`` — a seeded generator composing every existing
  injector into one randomized, virtual-time-stamped event list:
  abrupt crash/recover (``SimNet.crash_node`` — torn store appends,
  stale mempool checkpoints, no shutdown hooks), StoreFaultPlan disk
  errors and bit-flips, partitions, link latency/loss spikes,
  HostilePeer/GreedyPeer adversaries, transaction traffic, and
  scenario-driven mining on both sides of every cut.  Schedules are
  fully deterministic per seed and JSON-round-trippable.
- ``run_chaos`` — the orchestrator: applies a schedule to a live mesh
  of full persistent nodes, clears every fault in a deterministic
  epilogue, settles, and checks the global invariant suite at quiesce:
  ledger conservation on every node, convergence to one tip within
  bounded virtual time after the last fault clears, no node stuck
  serve-only once its disk healed, every crashed node's store
  fsck-clean (verdict 0/1, never 2) at recovery AND at shutdown, no
  resurrected already-mined transaction in any pool, and proof/filter
  caches consistent with the post-reorg chain.
- ``shrink_schedule`` — delta debugging (ddmin): on a violation, the
  schedule is minimized to the smallest event list that still
  reproduces it, and ``write_repro``/``run_repro`` round-trip a
  replayable artifact (seed + schedule + expected digest) through
  ``p1 chaos --repro``.

Determinism contract: the whole run — crash/recover cycles included —
hashes into the simulator's event-trace digest; two runs of one seed
are byte-identical in-process and across processes under
PYTHONHASHSEED (tests/test_chaos.py, tests/test_cli.py).

What the crash model does NOT capture (honesty, docs/ROUND11.md): the
torn-append artifact is the FaultStore's single-record tear — kernel
page-cache reordering that loses an EARLIER acknowledged write while a
later one survives is outside it (the store fsyncs per append, so that
scenario requires a lying disk, which round 7's writer-refusal covers
separately); fsync-reordering across the mempool checkpoint and the
store is likewise not modeled — the checkpoint is atomic-or-absent.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import random
import time
from pathlib import Path

from p1_tpu.node.netsim import NODE_PORT, LinkProfile, SimNet

__all__ = [
    "CHAOS_BUGS",
    "fsck_verdict",
    "generate_schedule",
    "generate_soak_schedule",
    "longevity_soak",
    "run_chaos",
    "run_repro",
    "shrink_schedule",
    "write_repro",
]

#: Repro artifact format tag (bump on layout change).
REPRO_FORMAT = "p1-chaos-repro-1"

#: Snapshot-sync joiners a schedule may spawn (node indices n_nodes..):
#: enough to compose join + crash + liar interactions, small enough to
#: keep tier-1 sweep runtimes flat.
MAX_JOINERS = 2
#: Checkpoint spacing every chaos node runs with: small enough that the
#: warmup + a couple of mine events cross a checkpoint, so joiners get
#: real snapshots to boot from inside short schedules.
SNAPSHOT_INTERVAL = 4

#: Live wallet watchers a schedule may run concurrently (slots 0..N-1):
#: enough to compose watch + crash + flood interactions, small enough
#: to keep tier-1 sweep runtimes flat (mirrors MAX_JOINERS).
MAX_WATCHERS = 2

#: Test-only injectable bugs, each a known-broken recovery behavior the
#: shrinker acceptance proof seeds deliberately (never reachable from
#: production config — only the ``--inject-bug`` flag threads them):
#:
#: - ``relapse-disk``: recovery silently re-arms the recovered node's
#:   disk fault — the "recovery declared the disk healthy without
#:   proving it" bug class; the node degrades serve-only on its first
#:   post-recover append and stays there, violating the serve-only
#:   invariant.
#: - ``deaf-recover``: the recovered node comes back with an empty peer
#:   list — the "recovered node rejoins nothing" bug class; when nobody
#:   happens to dial it, the mesh converges without it.
#: - ``mute-push``: a watcher's confirmations arrive stripped of their
#:   match — the "push plane silently drops the one event the wallet
#:   subscribed for" bug class; the zero-missed-confirmations invariant
#:   must flag it at quiesce.
CHAOS_BUGS = ("relapse-disk", "deaf-recover", "mute-push")


# -- schedule generation ---------------------------------------------------


def generate_schedule(
    seed: int,
    n_nodes: int,
    n_events: int = 12,
    horizon_vs: float = 30.0,
    txs: bool = True,
) -> list[dict]:
    """One randomized, well-formed fault schedule: ``n_events`` events
    at seeded virtual-time offsets in (0, ``horizon_vs``].  Well-formed
    means runnable, not balanced — crashes may outlive the schedule
    (the orchestrator's epilogue recovers everything), and any SUBSET
    of a generated schedule is also runnable (events on dead/absent
    targets degrade to no-ops), which is what lets the ddmin shrinker
    cut arbitrary chunks.

    Event ops and their composition sources:

    - ``mine`` — scenario-driven block production (both sides of a cut
      mine, so heals reorg);
    - ``tx`` — a signed wallet spend submitted to a live node (funds
      ride node 0's pinned coinbase identity);
    - ``crash`` / ``recover`` — abrupt death (optionally with a torn
      in-flight append) and resume-path reboot;
    - ``corrupt`` — flip one byte of a CRASHED node's store file
      (bit-rot while down; recovery must quarantine, never trust);
    - ``disk_fail`` / ``disk_heal`` — arm/clear a persistent
      StoreFaultPlan write error on a LIVE node (degrade→serve-only→
      supervised recovery, inside the adversarial mesh);
    - ``partition`` / ``heal`` — contiguous split (the backbone
      topology keeps both sides internally connected);
    - ``slow_link`` / ``restore_link`` — latency/jitter/loss spike on
      every link of one host;
    - ``hostile`` — a HostilePeer (stale or swallowed sync replies)
      dials a victim; ``flood`` — a GreedyPeer protocol-valid flood;
    - ``snap_join`` — a fresh snapshot-syncing node (``snapshot_sync``)
      joins the mesh mid-schedule: it boots ASSUMED from whatever
      snapshot a peer serves (or falls back to IBD) and must flip to
      fully-validated by quiesce.  Joiners live at indices >=
      ``n_nodes`` and are crash/recover/corrupt-eligible like everyone
      else — which is exactly how crash-during-snapshot-download and
      crash-during-background-revalidation compose into schedules;
    - ``snap_liar`` — a hostile SNAPSHOT SERVER (lying balances, a
      corrupted root, a truncated chunk stream, or a full stall) plus a
      joiner that dials it first and an honest node second: the joiner
      must detect/contain the lie and still converge;
    - ``stage_crash`` — a crash at one pipeline stage boundary
      (node/pipeline.py): validate/store arm a one-shot lane-worker
      death on a live node (respawn-and-retry must hold), the on-loop
      stages (frame/admission/relay) crash the process, stage-tagged;
    - ``rebase`` — a LIVE node advances its base via the maintenance
      plane (round 20) while mining and serving; the ``crash: true``
      variant runs the durable store half (seal + sidecar spill) and
      kills the process BEFORE the in-RAM rebase — the mid-rebase
      kill-9, which must reboot as an ordinary un-rebased node;
    - ``seal_sidecar_crash`` — the ``.sdx`` state-delta write fails at
      a forced seal (the tolerated sidecar failure family: the roll
      must land, the failure must count, the plane must self-heal);
    - ``online_prune`` / ``online_compact_crash`` — the round-20
      node-side maintenance commands: prune while serving, and a
      compaction whose off-loop planning dies mid-write (the node must
      self-clean the tmp artifacts and keep serving);
    - ``watch_start`` / ``watch_stop`` — a live wallet watcher
      (``client.watch`` over the sim transport, round 21) subscribes to
      the payee account against one node with the whole mesh as
      fallback, and later churns away.  Watchers still live at quiesce
      owe the push-plane invariant: a gap-free, commitment-verified
      stream to the converged tip with ZERO missed confirmations —
      crashes of the serving node mid-push, floods, and partitions
      included;
    - ``sub_flood`` — a GreedyPeer hammering the subscription plane
      (SUBSCRIBE churn plus unverifiable resume cursors): the
      degradation ladder and admission tables must shed it without
      harming honest watchers;
    - ``replica_kill`` / ``replica_join`` — the fleet-provisioning
      family (round 22): ``replica_kill`` crashes the node a live
      watcher's ReplicaSet is actively riding (the directed
      kill-one-replica, resolved at runtime from the wallet's own
      ``active`` pointer; the scheduled node is the fallback victim
      when no watcher is live), and ``replica_join`` spawns an honest
      snapshot-bootstrapped joiner AND rebalances every live watcher's
      ReplicaSet onto it (``update_targets``) — wallets must fail over
      and re-spread with ZERO missed confirmations either way.
    """
    rng = random.Random((seed << 3) ^ 0xC4A05)
    joiners: set[int] = set()
    watchers: set[int] = set()
    pruned_any = False
    rebased_any = False
    times = sorted(
        round(rng.uniform(0.5, horizon_vs), 3) for _ in range(n_events)
    )
    crashed: set[int] = set()
    disks_down: set[int] = set()
    slowed: set[int] = set()
    partitioned = False
    hostiles = 0
    events: list[dict] = []
    for at in times:
        ops = [("mine", 5.0)]
        if txs:
            ops.append(("tx", 2.0))
        if len(crashed) < max(1, n_nodes - 2):
            ops.append(("crash", 2.5))
        if crashed:
            ops.append(("recover", 2.0))
            ops.append(("corrupt", 1.0))
        if not partitioned and n_nodes >= 4:
            ops.append(("partition", 1.5))
        if partitioned:
            ops.append(("heal", 2.0))
        if len(disks_down) < n_nodes - 1:
            ops.append(("disk_fail", 1.5))
        if disks_down:
            ops.append(("disk_heal", 1.5))
        if len(slowed) < n_nodes - 1:
            ops.append(("slow_link", 1.0))
        if slowed:
            ops.append(("restore_link", 1.0))
        if hostiles < 2:
            ops.append(("hostile", 0.75))
            ops.append(("flood", 0.5))
        if len(joiners) < MAX_JOINERS:
            ops.append(("snap_join", 1.0))
            ops.append(("snap_liar", 0.75))
        # Wallet push plane (round 21): live watchers churn on and off
        # mid-schedule, and the subscription port takes protocol-valid
        # floods; a stopped slot may restart (the churn the soak's
        # ``subs`` clusters run at week scale).
        if len(watchers) < MAX_WATCHERS:
            ops.append(("watch_start", 1.0))
        if watchers:
            ops.append(("watch_stop", 0.5))
        if hostiles < 2:
            ops.append(("sub_flood", 0.5))
        # Fleet provisioning (round 22): kill the replica a wallet is
        # riding, and join a fresh one into live ReplicaSets.
        if watchers and len(crashed) < max(1, n_nodes - 2):
            ops.append(("replica_kill", 0.75))
        if len(joiners) < MAX_JOINERS:
            ops.append(("replica_join", 0.75))
        # Segmented-store plane (round 18).  ``seg_roll`` forces a live
        # node's active segment to seal mid-mesh; ``prune`` discards a
        # live node's deep body segments while it serves (at most one
        # pruned host per schedule — someone must keep the archive);
        # ``compact_crash`` drops the exact tmp-file artifact of a
        # compaction killed before its atomic replace onto a crashed
        # node's store.  All three degrade to no-ops on single-file
        # stores, keeping every subset runnable for the shrinker.
        ops.append(("seg_roll", 0.75))
        if not pruned_any:
            ops.append(("prune", 0.5))
        if crashed:
            ops.append(("compact_crash", 0.5))
        # Always-on maintenance plane (round 20): the node-side
        # zero-downtime operations, driven through the same _maintain
        # entry `p1 maintain` uses.  Re-basing and pruning both shrink
        # a host's deep-history serving capacity, so each is capped
        # like ``prune`` — someone must keep the archive.  All degrade
        # to no-ops on single-file stores or refused preconditions
        # (subset-runnability for the shrinker).
        if not rebased_any and len(crashed) < max(1, n_nodes - 2):
            ops.append(("rebase", 0.75))
        ops.append(("seal_sidecar_crash", 0.5))
        if not pruned_any:
            ops.append(("online_prune", 0.5))
        ops.append(("online_compact_crash", 0.5))
        # Staged-pipeline plane (round 19): a crash at every stage
        # boundary.  The two lane stages (validate/store) die as WORKER
        # deaths — the pipeline must respawn the lane and retry without
        # losing the job; the three on-loop stages (frame/admission/
        # relay) have no thread to kill, so their boundary crash IS a
        # process crash, recorded with the stage name.
        if len(crashed) < max(1, n_nodes - 2):
            ops.append(("stage_crash", 1.0))
        op = rng.choices([o for o, _ in ops], [w for _, w in ops])[0]
        ev: dict = {"at": at, "op": op}
        if op == "mine":
            ev["node"] = rng.randrange(n_nodes)
        elif op == "tx":
            ev["amount"] = rng.randrange(1, 5)
            ev["fee"] = rng.randrange(0, 3)
        elif op == "snap_join":
            slot = n_nodes + len(joiners)
            ev["node"] = slot
            ev["peers"] = sorted(rng.sample(range(n_nodes), min(2, n_nodes)))
            joiners.add(slot)
        elif op == "snap_liar":
            slot = n_nodes + len(joiners)
            ev["node"] = slot
            ev["peers"] = [rng.randrange(n_nodes)]
            ev["fault"] = rng.choice(("balance", "root", "truncate", "stall"))
            ev["height"] = rng.choice((8, 12))
            joiners.add(slot)
            hostiles += 1
        elif op == "crash":
            universe = [*range(n_nodes), *sorted(joiners)]
            victims = [i for i in universe if i not in crashed]
            ev["node"] = rng.choice(victims)
            # 0 = clean kill; >0 seeds the torn-append offset.
            ev["torn"] = rng.choice((0, 0, rng.randrange(1, 1 << 16)))
            crashed.add(ev["node"])
            disks_down.discard(ev["node"])  # a dead process holds no plan
        elif op == "recover":
            ev["node"] = rng.choice(sorted(crashed))
            crashed.discard(ev["node"])
        elif op == "corrupt":
            ev["node"] = rng.choice(sorted(crashed))
            ev["offset"] = rng.randrange(1 << 20)
        elif op == "partition":
            ev["frac"] = rng.choice((0.3, 0.5, 0.7))
            partitioned = True
        elif op == "heal":
            partitioned = False
        elif op == "disk_fail":
            import errno

            up = [i for i in range(n_nodes) if i not in disks_down]
            ev["node"] = rng.choice(up)
            ev["errno"] = rng.choice((errno.ENOSPC, errno.EIO))
            disks_down.add(ev["node"])
        elif op == "disk_heal":
            ev["node"] = rng.choice(sorted(disks_down))
            disks_down.discard(ev["node"])
        elif op == "slow_link":
            cands = [i for i in range(n_nodes) if i not in slowed]
            ev["node"] = rng.choice(cands)
            ev["latency_ms"] = rng.choice((50, 150, 400))
            ev["loss"] = rng.choice((0.0, 0.2, 0.5))
            slowed.add(ev["node"])
        elif op == "restore_link":
            ev["node"] = rng.choice(sorted(slowed))
            slowed.discard(ev["node"])
        elif op == "hostile":
            ev["node"] = rng.randrange(n_nodes)
            ev["fault"] = rng.choice(("stale", "swallow"))
            ev["height"] = rng.randrange(3, 9)
            hostiles += 1
        elif op == "flood":
            ev["node"] = rng.randrange(n_nodes)
            ev["kind"] = rng.choice(("queries", "blocks"))
            hostiles += 1
        elif op == "watch_start":
            slot = min(s for s in range(MAX_WATCHERS) if s not in watchers)
            ev["watcher"] = slot
            ev["node"] = rng.randrange(n_nodes)
            watchers.add(slot)
        elif op == "replica_kill":
            # The true victim (a live watcher's active target) is only
            # knowable at runtime; ``node`` is the fallback victim and
            # the conservative bookkeeping entry — whoever actually
            # dies, later events on dead targets degrade to no-ops.
            victims = [i for i in range(n_nodes) if i not in crashed]
            ev["node"] = rng.choice(victims)
            crashed.add(ev["node"])
        elif op == "replica_join":
            slot = n_nodes + len(joiners)
            ev["node"] = slot
            ev["peers"] = sorted(rng.sample(range(n_nodes), min(2, n_nodes)))
            joiners.add(slot)
        elif op == "watch_stop":
            ev["watcher"] = rng.choice(sorted(watchers))
            watchers.discard(ev["watcher"])
        elif op == "sub_flood":
            ev["node"] = rng.randrange(n_nodes)
            hostiles += 1
        elif op == "seg_roll":
            ev["node"] = rng.randrange(n_nodes)
        elif op == "prune":
            ev["node"] = rng.randrange(n_nodes)
            ev["keep"] = rng.choice((2, 4))
            pruned_any = True
        elif op == "compact_crash":
            ev["node"] = rng.choice(sorted(crashed))
            ev["junk"] = rng.randrange(1, 1 << 16)
        elif op == "rebase":
            victims = [i for i in range(n_nodes) if i not in crashed]
            ev["node"] = rng.choice(victims)
            # Small keeps: a 30-vs schedule mines ~a dozen blocks, and
            # a keep past the chain height degrades the event to a
            # refusal no-op every time (we want SOME organic fires).
            ev["keep"] = rng.choice((2, 4))
            ev["crash"] = rng.random() < 0.34
            rebased_any = True
            if ev["crash"]:
                # The mid-rebase kill: the process dies after the store
                # half — downstream scheduling must treat it as crashed.
                crashed.add(ev["node"])
                disks_down.discard(ev["node"])
        elif op == "seal_sidecar_crash":
            ev["node"] = rng.randrange(n_nodes)
        elif op == "online_prune":
            ev["node"] = rng.randrange(n_nodes)
            ev["keep"] = rng.choice((2, 4))
            pruned_any = True
        elif op == "online_compact_crash":
            ev["node"] = rng.randrange(n_nodes)
        elif op == "stage_crash":
            from p1_tpu.node.pipeline import LANE_STAGES, STAGES

            universe = [*range(n_nodes), *sorted(joiners)]
            victims = [i for i in universe if i not in crashed]
            ev["node"] = rng.choice(victims)
            ev["stage"] = rng.choice(STAGES)
            if ev["stage"] not in LANE_STAGES:
                # On-loop stage boundary: the process dies (clean kill —
                # torn appends belong to the plain crash op).
                crashed.add(ev["node"])
                disks_down.discard(ev["node"])
        events.append(ev)
    return events


# -- longevity soak --------------------------------------------------------

#: One virtual day, seconds.
DAY_VS = 86_400.0


def generate_soak_schedule(
    seed: int,
    n_nodes: int,
    horizon_vs: float,
    fault_clusters: int,
    blocks: int,
    txs_per_cluster: int = 2,
    fault_window_vs: float = 240.0,
) -> list[dict]:
    """A LONG-horizon schedule shaped for longevity, not density: the
    same event vocabulary as ``generate_schedule``, but every
    disruptive fault is paired with its clearing event inside a bounded
    ``fault_window_vs`` envelope (crash→recover, partition→heal,
    disk_fail→disk_heal, slow_link→restore_link, hostile/flood→calm,
    watch_start→watch_stop).
    A week-long open partition is the partition-heal scenario's
    question; the longevity question is whether a week of RECURRING
    fault/heal cycles, steady mining, and wallet traffic leaves any
    monotone growth behind — so faults here recur and clear, block
    production ticks through the whole horizon, and two ``probe``
    events (midpoint and end) snapshot the per-node leak gauges the
    quiesce invariants compare.

    The envelope also keeps the event count proportional to the fault
    count rather than the horizon: an unclosed crash would have every
    surviving peer redialing the corpse twice a second for six virtual
    days (RECONNECT_DELAY_S), drowning the run in events the scenario
    never meant to test."""
    rng = random.Random((seed << 4) ^ 0x50AC7)
    events: list[dict] = []
    maintained = 0
    for b in range(blocks):
        at = (b + 1) * horizon_vs / (blocks + 1)
        events.append(
            {"at": round(at, 3), "op": "mine", "node": b % n_nodes}
        )
    slot_vs = horizon_vs / max(1, fault_clusters)
    assert slot_vs > fault_window_vs + 2.0, (
        "fault clusters overlap: lengthen the horizon or reduce clusters"
    )
    joiners = 0
    for c in range(fault_clusters):
        at = round(c * slot_vs + rng.uniform(1.0, slot_vs - fault_window_vs - 1.0), 3)
        end = round(at + rng.uniform(30.0, fault_window_vs), 3)
        kind = rng.choice(
            (
                "crash",
                "crash",
                "partition",
                "disk_fail",
                "slow_link",
                "hostile",
                "flood",
                "snap_join",
                "maintenance",
                "subs",
            )
        )
        if kind == "crash":
            victim = rng.randrange(n_nodes)
            events.append(
                {
                    "at": at,
                    "op": "crash",
                    "node": victim,
                    "torn": rng.choice((0, rng.randrange(1, 1 << 16))),
                }
            )
            events.append({"at": end, "op": "recover", "node": victim})
            if rng.random() < 0.5:
                events.append(
                    {
                        "at": round((at + end) / 2, 3),
                        "op": "corrupt",
                        "node": victim,
                        "offset": rng.randrange(1 << 20),
                    }
                )
        elif kind == "partition":
            events.append(
                {
                    "at": at,
                    "op": "partition",
                    "frac": rng.choice((0.3, 0.5, 0.7)),
                }
            )
            events.append({"at": end, "op": "heal"})
        elif kind == "disk_fail":
            import errno

            victim = rng.randrange(n_nodes)
            events.append(
                {
                    "at": at,
                    "op": "disk_fail",
                    "node": victim,
                    "errno": rng.choice((errno.ENOSPC, errno.EIO)),
                }
            )
            events.append({"at": end, "op": "disk_heal", "node": victim})
        elif kind == "slow_link":
            victim = rng.randrange(n_nodes)
            events.append(
                {
                    "at": at,
                    "op": "slow_link",
                    "node": victim,
                    "latency_ms": rng.choice((50, 150, 400)),
                    "loss": rng.choice((0.0, 0.2)),
                }
            )
            events.append(
                {"at": end, "op": "restore_link", "node": victim}
            )
        elif kind == "hostile":
            events.append(
                {
                    "at": at,
                    "op": "hostile",
                    "node": rng.randrange(n_nodes),
                    "fault": rng.choice(("stale", "swallow")),
                    "height": rng.randrange(3, 9),
                }
            )
            events.append({"at": end, "op": "calm"})
        elif kind == "flood":
            events.append(
                {
                    "at": at,
                    "op": "flood",
                    "node": rng.randrange(n_nodes),
                    "kind": rng.choice(("queries", "blocks")),
                }
            )
            events.append({"at": end, "op": "calm"})
        elif kind == "snap_join" and joiners < MAX_JOINERS:
            slot = n_nodes + joiners
            joiners += 1
            events.append(
                {
                    "at": at,
                    "op": "snap_join",
                    "node": slot,
                    "peers": sorted(
                        rng.sample(range(n_nodes), min(2, n_nodes))
                    ),
                }
            )
        elif kind == "maintenance":
            # A round-20 maintenance cycle inside one fault envelope:
            # sidecar-failure at a seal, then a live re-base, then
            # either an online prune (FIRST cluster only — someone
            # must keep the archive over a week of clusters) or a
            # compaction with its planning failure injected.  Recurring
            # across a virtual week, this is exactly the "always-on
            # node" longevity question: does repeated self-maintenance
            # leak or drift anything the quiesce gauges can see?
            victim = rng.randrange(n_nodes)
            events.append(
                {"at": at, "op": "seal_sidecar_crash", "node": victim}
            )
            events.append(
                {
                    "at": round((at + end) / 2, 3),
                    "op": "rebase",
                    "node": victim,
                    "keep": 8,
                    "crash": False,
                }
            )
            if maintained == 0:
                events.append(
                    {
                        "at": end,
                        "op": "online_prune",
                        "node": victim,
                        "keep": 4,
                    }
                )
            else:
                events.append(
                    {"at": end, "op": "online_compact_crash", "node": victim}
                )
            maintained += 1
        elif kind == "subs":
            # Subscription churn (round 21): a wallet rides the push
            # plane across the envelope, then unsubscribes.  Recurring
            # subscribe/consume/drop cycles are the push plane's
            # longevity question — does a week of watcher churn leave
            # sessions, queue bytes, or registry entries behind for the
            # quiesce gauges to see?
            events.append(
                {
                    "at": at,
                    "op": "watch_start",
                    "node": rng.randrange(n_nodes),
                    "watcher": c % MAX_WATCHERS,
                }
            )
            # A block inside the envelope, so every churn cycle carries
            # at least one real push before the watcher unsubscribes.
            events.append(
                {
                    "at": round(at + (end - at) * 0.75, 3),
                    "op": "mine",
                    "node": rng.randrange(n_nodes),
                }
            )
            events.append(
                {"at": end, "op": "watch_stop", "watcher": c % MAX_WATCHERS}
            )
        for _ in range(txs_per_cluster):
            events.append(
                {
                    "at": round(rng.uniform(at, end), 3),
                    "op": "tx",
                    "amount": rng.randrange(1, 5),
                    "fee": rng.randrange(0, 3),
                }
            )
    events.append({"at": round(horizon_vs / 2, 3), "op": "probe"})
    events.append({"at": round(horizon_vs, 3), "op": "probe"})
    return sorted(events, key=lambda e: e["at"])


def longevity_soak(
    seed: int = 0,
    nodes: int = 5,
    days: float = 7.0,
    clusters_per_day: float = 4.0,
    blocks_per_day: float = 48.0,
    difficulty: int = 8,
    settle_vs: float = 240.0,
    rss_bound_mb: float = 2048.0,
    wall_limit_s: float | None = 600.0,
) -> dict:
    """The ≥1-virtual-week longevity soak (ROADMAP item 4): ``days`` of
    virtual mesh life — steady block production, recurring
    fault/heal cycles across every injector family, wallet traffic —
    compressed through the chaos plane's virtual clock, then held to
    the full quiesce invariant suite PLUS the leak invariants the probe
    events feed: bounded RSS, ban/violation tables, address books,
    signature/proof/filter caches, per-node task counts, and
    supervision/store retry counters whose second-half growth must stay
    proportional to the first half (a runaway retry loop shows up as a
    hockey stick even when every individual table is capped).

    Returns a scenario-shaped report (``p1 sim soak`` runs it): chaos
    report fields + ``scenario``/``repro`` stamps, ``ok`` iff zero
    violations."""
    horizon_vs = days * DAY_VS
    events = generate_soak_schedule(
        seed,
        nodes,
        horizon_vs,
        fault_clusters=max(1, round(days * clusters_per_day)),
        blocks=max(1, round(days * blocks_per_day)),
    )
    report = run_chaos(
        seed,
        nodes=nodes,
        events=events,
        difficulty=difficulty,
        settle_vs=settle_vs,
        wall_limit_s=wall_limit_s,
        rss_bound_mb=rss_bound_mb,
    )
    report["scenario"] = "soak"
    report["days_virtual"] = round(report["virtual_s"] / DAY_VS, 3)
    report["repro"] = f"p1 sim soak --seed {seed}"
    return report


def _vm_rss_mb() -> float | None:
    """Current process RSS in MB via /proc (None off-Linux)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        return None
    return None


# -- store verdicts --------------------------------------------------------


def fsck_verdict(path) -> int:
    """The `p1 fsck` exit-code contract as a pure function of the store
    file's bytes: 0 = clean framing, 1 = damage a salvage recovers
    (torn tail / quarantinable spans — at least one good record or an
    empty-but-valid log survives), 2 = unrecoverable (missing, not a
    chain store, or nothing salvageable).  The chaos invariant: a
    crashed node's store must NEVER reach 2 — whatever the schedule
    did, recovery has something valid to stand on.

    Segmented stores (chain/segstore.py) verdict per SEGMENT straight
    off the directory — the manifest is a rebuildable cache, so only an
    unscannable segment (destroyed magic) is unrecoverable; stray
    segments from a mid-roll crash are scanned too."""
    from p1_tpu.chain import segstore
    from p1_tpu.chain.store import ChainStore

    path = Path(path)
    if not path.exists():
        return 2
    if segstore.is_segmented(path):
        seg_dir = path.with_name(path.name + ".d")
        files = (
            sorted(seg_dir.glob("seg*.p1s")) if seg_dir.exists() else []
        )
        if not files:
            # Fully-pruned stores keep their .hdrx plane; anything else
            # with zero segments lost the archive wholesale.
            hdrx = (
                list(seg_dir.glob("seg*.hdrx")) if seg_dir.exists() else []
            )
            return 0 if hdrx else 2
        worst = 0
        for f in files:
            data = f.read_bytes()
            if not data or segstore._torn_magic(data):
                worst = max(worst, 1)  # torn first write: heals empty
                continue
            try:
                scan = ChainStore.scan(data)
            except ValueError:
                return 2
            if not scan.clean:
                worst = max(worst, 1)
        return worst
    data = path.read_bytes()
    try:
        scan = ChainStore.scan(data)
    except ValueError:
        return 2
    if scan.clean:
        return 0
    # Damaged but salvageable as long as the framing walk itself stood
    # up (it did — scan returned).  A store reduced to bad spans only
    # still salvages to a valid empty log, which resyncs from peers.
    return 1


# -- the orchestrator ------------------------------------------------------


def run_chaos(
    seed: int,
    nodes: int = 6,
    n_events: int = 12,
    events: list[dict] | None = None,
    difficulty: int = 8,
    store_dir=None,
    horizon_vs: float = 30.0,
    settle_vs: float = 240.0,
    wall_limit_s: float | None = 180.0,
    inject_bug: str | None = None,
    txs: bool = True,
    keep_trace: bool = False,
    rss_bound_mb: float | None = None,
    pipeline_workers: int = 0,
    recon: bool = False,
) -> dict:
    """Run one chaos schedule end to end and return the report.

    ``events`` replays an explicit schedule (the repro path); None
    generates one from the seed.  ``store_dir`` holds every node's
    on-disk state for the run; None uses a private temp directory.
    ``inject_bug`` (test-only, see ``CHAOS_BUGS``) seeds a known
    recovery bug so the shrinker pipeline can be proven against a
    violation that is guaranteed to exist.

    Report: ``ok`` iff every invariant held; ``violations`` lists
    ``{"invariant", "detail"}`` rows; ``trace_digest`` is the
    simulator's running event hash — the replay-identity witness.
    """
    assert inject_bug is None or inject_bug in CHAOS_BUGS, inject_bug
    if events is None:
        events = generate_schedule(
            seed, nodes, n_events, horizon_vs=horizon_vs, txs=txs
        )
    if store_dir is None:
        import tempfile

        with tempfile.TemporaryDirectory(prefix="p1chaos") as tmp:
            return run_chaos(
                seed,
                nodes=nodes,
                events=events,
                difficulty=difficulty,
                store_dir=tmp,
                settle_vs=settle_vs,
                wall_limit_s=wall_limit_s,
                inject_bug=inject_bug,
                txs=txs,
                keep_trace=keep_trace,
                rss_bound_mb=rss_bound_mb,
                pipeline_workers=pipeline_workers,
                recon=recon,
            )
    t0 = time.monotonic()
    net = SimNet(
        seed=seed,
        difficulty=difficulty,
        store_dir=store_dir,
        keep_trace=keep_trace,
        # Round 18: the whole schedule corpus runs over SEGMENTED
        # stores (tiny segments, so a few mined blocks cross roll
        # boundaries) — crashes/torn writes/bit-rot now land on segment
        # files, and the fsck invariant verdicts per segment.
        segmented_store=store_dir is not None,
        # Round 19: staged-node sweeps run the whole corpus with lane
        # workers enabled; the virtual loop keeps lane jobs synchronous
        # (SimLoop.run_in_executor), so the digest stays seed-stable.
        pipeline_workers=pipeline_workers,
    )
    runner = _ChaosRunner(
        net, nodes, difficulty, inject_bug, settle_vs, wall_limit_s,
        rss_bound_mb=rss_bound_mb, recon=recon,
    )
    report = net.run(runner.main(events))
    report["seed"] = seed
    report["nodes"] = nodes
    report["repro"] = f"p1 chaos --seed {seed} --nodes {nodes}"
    if rss_bound_mb is not None:
        # RSS at quiesce vs the soak bound — read here, OUTSIDE the
        # event loop (the probe path stays pure reads, and /proc IO
        # never lands on the loop the transitive-blocking lint guards).
        # VmRSS, not peak: CPython's allocator rarely returns freed
        # arenas, but a bounded-table mesh at quiesce must still fit.
        rss_mb = _vm_rss_mb()
        report["rss_mb"] = rss_mb
        report["rss_bound_mb"] = rss_bound_mb
        if rss_mb is not None and rss_mb > rss_bound_mb:
            report["violations"].append(
                {
                    "invariant": "rss",
                    "detail": f"process RSS {rss_mb:.0f} MB over the "
                    f"{rss_bound_mb:.0f} MB soak bound at quiesce",
                }
            )
    report["wall_s"] = round(time.monotonic() - t0, 3)
    report["ok"] = not report["violations"]
    return report


class _Watcher:
    """One live wallet on the push plane: ``client.watch`` driven over
    the sim transport against a primary node with the whole founder
    mesh as fallback, recording every VERIFIED event for the quiesce
    invariants.  The watch itself is deterministic (no randomness, no
    wall clock), so watchers ride the trace-digest contract like any
    other actor.

    ``floor`` is the strict-coverage floor: the height below the first
    verified event, pushed UP whenever the stream re-anchors past a
    hole (a reorg deeper than the rewind ring resets the TOFU anchor —
    the wallet would rescan history below it, so the gap-free claim
    restarts there).  ``resets`` counts those holes."""

    def __init__(
        self, net, serial, primary, fallbacks, item, difficulty, mute=False
    ):
        from p1_tpu.node.client import ReplicaSet

        self.net = net
        self.serial = serial
        self.primary = primary
        self.targets = [(primary, NODE_PORT)] + [
            (h, NODE_PORT) for h in fallbacks if h != primary
        ]
        self.item = item
        self.difficulty = difficulty
        self.mute = mute
        # The wallet-side fleet policy (round 22): health-scored target
        # selection with live rebalancing.  spread_key=0 keeps the
        # schedule's named primary as the first dial (all targets start
        # tied, join order breaks the tie), so schedule semantics read
        # the same as the old rotation — the policy differences show up
        # under faults, which is where they belong.
        self.rs = ReplicaSet(self.targets, spread_key=0)
        self.events: list[dict] = []
        self.by_height: dict[int, dict] = {}  # height -> LAST event there
        self.floor: int | None = None
        self.resets = 0
        self.error: str | None = None
        self._last_h: int | None = None
        self._task: asyncio.Task | None = None

    def add_target(self, host: str) -> None:
        """A freshly provisioned replica joined the serving set: fold it
        into the live watch's ReplicaSet (op ``replica_join``)."""
        t = (host, NODE_PORT)
        if t not in self.targets:
            self.targets.append(t)
            self.rs.update_targets(self.targets)

    @property
    def live(self) -> bool:
        return self._task is not None and not self._task.done()

    @property
    def tip_height(self) -> int:
        """The watch's CURRENT verified position (not its max — after a
        reorg rewind the re-pushed branch is where the stream stands)."""
        return -1 if self._last_h is None else self._last_h

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        from p1_tpu.node import client

        transport = self.net.net.host(f"77.7.0.{self.serial}")
        try:
            # cross_check_every=0: the per-event commitment verification
            # (header link, PoW, H-link) stays on; the periodic
            # cross-replica audit is OFF because an honest mesh mid-fork
            # genuinely disagrees — when the fork point predates this
            # watch's ring, adjudication resolves conservatively by
            # demoting the serving peer, and a week of partitions would
            # slowly demote honest nodes.  The audit path is proven by
            # the lying-replica suites (tests/test_subscriptions.py).
            async for ev in client.watch(
                self.targets[0][0],
                NODE_PORT,
                [self.item],
                self.difficulty,
                replica_set=self.rs,
                transport=transport,
                cross_check_every=0,
                reconnect_delay_s=0.5,
                max_session_failures=None,
            ):
                h = ev["height"]
                if self.floor is None:
                    self.floor = h - 1
                elif self._last_h is not None and h > self._last_h + 1:
                    self.resets += 1
                    self.floor = h - 1
                self._last_h = h
                if self.mute and ev["matched"]:
                    # Injected bug (``mute-push``): the confirmation
                    # arrives stripped of its match — exactly what the
                    # zero-missed-confirmations invariant must catch.
                    ev = {**ev, "matched": False, "txids": ()}
                self.events.append(ev)
                self.by_height[h] = ev
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — recorded, judged at quiesce
            self.error = f"{type(e).__name__}: {e}"


class _ChaosRunner:
    """One schedule's execution state (hosts, wallets, live actors)."""

    def __init__(self, net, n_nodes, difficulty, inject_bug, settle_vs,
                 wall_limit_s, rss_bound_mb=None, recon=False):
        from p1_tpu.core.keys import Keypair

        self.net = net
        self.n = n_nodes
        self.difficulty = difficulty
        self.inject_bug = inject_bug
        self.settle_vs = settle_vs
        self.wall_limit_s = wall_limit_s
        # Base mesh hosts, then the (lazily spawned) snapshot-joiner
        # slots — one flat list so every schedule index resolves the
        # same way whether it names a founder or a joiner.
        self.hosts = [net.host_name(i) for i in range(n_nodes)] + [
            f"10.99.0.{k}" for k in range(MAX_JOINERS)
        ]
        self.joiner_hosts = self.hosts[n_nodes:]
        #: (host, height, tip hash, wallet balance) reported by joiners
        #: WHILE in the ASSUMED state — checked against the validated
        #: history at quiesce (the never-contradicted invariant).
        self.samples: list[tuple] = []
        # Deterministic wallet: node 0 mines to this account, so its
        # spends are funded the moment the warmup blocks land.
        self.wallet = Keypair.from_seed_text(f"p1-chaos-{net.seed}")
        self.payee = Keypair.from_seed_text(f"p1-chaos-{net.seed}-payee")
        self.actors: list = []  # hostile/greedy peers, stopped at epilogue
        #: Live wallet watchers by schedule slot; churned-away ones move
        #: to ``retired_watchers`` (still judged for honesty at quiesce).
        self.watchers: dict[int, _Watcher] = {}
        self.retired_watchers: list[_Watcher] = []
        self.watch_serial = 0
        self.slowed: set[str] = set()
        self.partitioned = False
        self.rss_bound_mb = rss_bound_mb
        #: Round 23: run the whole mesh with set-reconciliation tx
        #: gossip on (no deployment table — recon from block 0).
        #: OPT-IN so the seed-stable trace-digest corpus keeps its
        #: recorded hashes; the recon sweep pins its own.
        self.recon = recon
        #: Leak-gauge snapshots taken by ``probe`` events (the soak
        #: schedule places one at the midpoint and one at the horizon);
        #: the quiesce leak invariants compare the last two.
        self.probes: list[dict] = []
        self.recover_verdicts: list[int] = []
        self.counts = {
            "applied": 0,
            "crashes": 0,
            "recoveries": 0,
            "txs": 0,
            "watchers": 0,
        }

    # -- helpers ----------------------------------------------------------

    def _alive(self, idx: int, mining: bool = False) -> str | None:
        """Resolve a schedule's node index to a LIVE host, walking
        forward deterministically when the named one is down (subsets
        of a schedule must stay runnable).  ``mining`` additionally
        skips degraded serve-only nodes: they reject even their own
        sealed blocks (by design), so a mine event on one is a no-op
        the schedule did not intend."""
        for k in range(self.n):
            host = self.hosts[(idx + k) % self.n]
            node = self.net.nodes.get(host)
            if node is None:
                continue
            if mining and node._store_degraded:
                continue
            return host
        return None

    def _record(self, *fields) -> None:
        # Chaos actions are trace events: the digest must pin the
        # schedule as executed, not just its network side effects.
        self.net.net._record("chaos", self.net.clock.now, *fields)

    # -- event application -------------------------------------------------

    async def _apply(self, ev: dict) -> None:
        net = self.net
        op = ev["op"]
        if op == "mine":
            host = self._alive(ev["node"], mining=True)
            if host is not None:
                self._record("mine", host)
                await net.mine_on(net.nodes[host])
        elif op == "tx":
            from p1_tpu.core.genesis import genesis_hash
            from p1_tpu.core.tx import Transaction

            host = self._alive(0)
            if host is None:
                return
            node = net.nodes[host]
            acct = self.wallet.account
            seq = node.mempool.pending_next_seq(acct, node.chain.nonce(acct))
            tx = Transaction.transfer(
                self.wallet,
                self.payee.account,
                ev["amount"],
                ev["fee"],
                seq,
                chain=genesis_hash(self.difficulty),
            )
            self._record("tx", host, seq)
            await node.submit_tx(tx)
            self.counts["txs"] += 1
        elif op == "crash":
            host = self.hosts[ev["node"]]
            if host in net.nodes:
                await net.crash_node(host, torn=ev.get("torn", 0))
                self.counts["crashes"] += 1
        elif op == "recover":
            host = self.hosts[ev["node"]]
            if host in net.crashed:
                await self._recover(host)
        elif op == "corrupt":
            from p1_tpu.chain.segstore import is_segmented

            host = self.hosts[ev["node"]]
            if host not in net.crashed:
                return  # only a DOWN node's disk rots unobserved
            path = Path(net.configs[host].store_path)
            if is_segmented(path):
                # Segmented layout: the rot lands in a SEGMENT file
                # (the manifest is a rebuildable cache, and destroying
                # it would model a different fault than record bit-rot).
                seg_dir = path.with_name(path.name + ".d")
                segs = [
                    f
                    for f in sorted(seg_dir.glob("seg*.p1s"))
                    if f.stat().st_size > 9
                ]
                if not segs:
                    return
                path = segs[ev["offset"] % len(segs)]
            data = bytearray(path.read_bytes())
            if len(data) <= 9:
                return  # magic only: nothing to rot
            # Never the magic: a destroyed format tag is fsck verdict 2
            # by definition, and this event models bit-rot in records.
            off = 8 + ev["offset"] % (len(data) - 8)
            data[off] ^= 0x20
            path.write_bytes(bytes(data))
            self._record("corrupt", host, off)
        elif op == "seg_roll":
            host = self.hosts[ev["node"]]
            store = net.stores.get(host)
            if (
                host in net.nodes
                and store is not None
                and hasattr(store, "roll_segment")
            ):
                try:
                    store.roll_segment()
                except OSError:
                    pass  # an armed disk-fault plan owns this failure
                else:
                    self._record("seg_roll", host)
        elif op == "prune":
            host = self.hosts[ev["node"]]
            node = net.nodes.get(host)
            store = net.stores.get(host)
            if (
                node is None
                or store is None
                or not hasattr(store, "prune_below")
            ):
                return
            floor = max(0, node.chain.height - ev["keep"])
            try:
                n = store.prune_below(floor)
            except OSError:
                return  # armed disk fault: the node's paths degrade
            if n:
                # Prune-while-serving: the node now refuses block sync
                # into the pruned range (peers fail over to the archive
                # holders) and a later crash/recover re-IBDs through
                # the mesh — both paths the invariants then check.
                node.chain.prune_floor = store.pruned_below
                self._record("prune", host, floor, n)
        elif op == "compact_crash":
            host = self.hosts[ev["node"]]
            if host not in net.crashed:
                return
            path = Path(net.configs[host].store_path)
            seg_dir = path.with_name(path.name + ".d")
            segs = (
                sorted(seg_dir.glob("seg*.p1s")) if seg_dir.exists() else []
            )
            if not segs:
                return
            victim = segs[ev["junk"] % len(segs)]
            # The exact artifact of a per-segment compaction killed
            # before its atomic os.replace: a partial sibling tmp.
            # Recovery must ignore it (verdict <= 1, records intact).
            tmp = victim.with_name(f"{victim.name}.seg.{ev['junk']}")
            tmp.write_bytes(b"P1TPUCH3" + bytes([ev["junk"] & 0xFF]) * 64)
            self._record("compact_crash", host)
        elif op == "rebase":
            host = self._alive(ev["node"])
            if host is None:
                return
            node = net.nodes[host]
            store = net.stores.get(host)
            if store is None or not hasattr(store, "ensure_sidecars"):
                return  # no segmented spill plane: nothing to rebase onto
            if ev.get("crash"):
                # Kill-9 mid-rebase: the durable store half (seal +
                # sidecar spill) lands, the process dies BEFORE the
                # in-RAM rebase.  Reboot must come back as an ordinary
                # un-rebased node (fsck <= 1, records an exact prefix)
                # with the spare sidecars simply awaiting reuse.
                try:
                    store.roll_segment()
                    store.ensure_sidecars()
                except OSError:
                    return  # an armed disk-fault plan owns this failure
                self._record("rebase_crash", host)
                await net.crash_node(host, torn=0)
                self.counts["crashes"] += 1
                return
            reply = await node._maintain(
                {"op": "rebase", "keep": ev["keep"]}
            )
            # Refusals (short chain, assumed posture, degraded store)
            # are fine — the event degrades to a no-op, which is what
            # keeps arbitrary schedule subsets runnable for the
            # shrinker.
            if reply.get("ok"):
                self._record("rebase", host, reply["new_base"])
        elif op == "seal_sidecar_crash":
            host = self._alive(ev["node"])
            store = net.stores.get(host) if host is not None else None
            if (
                host is None
                or store is None
                or not hasattr(store, "fail_next_sidecar")
            ):
                return
            before = store.healed["sdx_failures"]
            store.fail_next_sidecar = True
            try:
                store.roll_segment()
            except OSError:
                return  # an armed disk-fault plan owns this failure
            finally:
                # An empty active segment skips the roll and leaves the
                # seam armed — disarm so a later organic seal does not
                # inherit this event's fault.
                store.fail_next_sidecar = False
            if store.healed["sdx_failures"] > before:
                self._record("seal_sidecar_crash", host)
        elif op == "online_prune":
            host = self._alive(ev["node"])
            if host is None:
                return
            reply = await net.nodes[host]._maintain(
                {"op": "prune", "keep": ev["keep"]}
            )
            if reply.get("ok") and reply.get("segments_pruned"):
                self._record(
                    "online_prune", host, reply["segments_pruned"]
                )
        elif op == "online_compact_crash":
            host = self._alive(ev["node"])
            store = net.stores.get(host) if host is not None else None
            if (
                host is None
                or store is None
                or not hasattr(store, "fail_next_compact")
            ):
                return
            # The off-loop planner dies mid-write (a partial tmp on
            # disk): the node must self-clean the artifact, degrade
            # cleanly, and recover — while every session it was serving
            # stays connected.
            store.fail_next_compact = True
            reply = await net.nodes[host]._maintain({"op": "compact"})
            store.fail_next_compact = False
            self._record(
                "online_compact_crash", host, int(bool(reply.get("ok")))
            )
        elif op == "stage_crash":
            from p1_tpu.node.pipeline import LANE_STAGES

            stage = ev["stage"]
            if stage in LANE_STAGES:
                # Lane-worker death on a LIVE node: the pipeline must
                # respawn the lane and retry the job (fires inline at
                # pipeline_workers=0 too, so the sim exercises the same
                # accounting) — the invariants then prove nothing was
                # lost at the boundary.
                host = self._alive(ev["node"])
                if host is not None:
                    self._record("stage_crash", host, stage)
                    net.nodes[host].pipeline.fail_next(stage)
            else:
                # On-loop stage boundary: no thread to kill — the
                # process dies, stage-tagged in the trace.
                host = self.hosts[ev["node"]]
                if host in net.nodes:
                    self._record("stage_crash", host, stage)
                    await net.crash_node(host, torn=0)
                    self.counts["crashes"] += 1
        elif op == "partition":
            k = max(1, min(self.n - 1, int(self.n * ev["frac"])))
            self.partitioned = True
            net.net.partition(self.hosts[:k], self.hosts[k:])
        elif op == "heal":
            if self.partitioned:
                self.partitioned = False
                net.net.heal()
        elif op == "disk_fail":
            from p1_tpu.chain.testing import StoreFaultPlan

            host = self.hosts[ev["node"]]
            store = net.stores.get(host)
            if host in net.nodes and store is not None:
                self._record("disk_fail", host, ev["errno"])
                store.plan = StoreFaultPlan(
                    fail_writes_from=store.writes + 1,
                    write_errno=ev["errno"],
                )
        elif op == "disk_heal":
            host = self.hosts[ev["node"]]
            store = net.stores.get(host)
            if store is not None:
                self._record("disk_heal", host)
                store.clear_faults()
        elif op == "slow_link":
            host = self.hosts[ev["node"]]
            self.slowed.add(host)
            self._record("slow_link", host, ev["latency_ms"], ev["loss"])
            profile = LinkProfile(
                latency_s=ev["latency_ms"] / 1e3,
                jitter_s=ev["latency_ms"] / 4e3,
                loss=ev["loss"],
            )
            for other in self.hosts:
                if other != host:
                    net.net.set_profile(host, other, profile)
        elif op == "restore_link":
            self._restore_link(self.hosts[ev["node"]])
        elif op == "hostile":
            from p1_tpu.node.protocol import MsgType
            from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

            victim = self._alive(ev["node"])
            if victim is None:
                return
            src = f"66.6.0.{len(self.actors)}"
            plan = (
                FaultPlan(stale_replies=True)
                if ev["fault"] == "stale"
                else FaultPlan(swallow=frozenset({MsgType.GETBLOCKS}))
            )
            hp = HostilePeer(
                make_blocks(ev["height"], self.difficulty),
                plan=plan,
                transport=net.net.host(src),
                host=src,
                rng=random.Random(net.seed * 101 + len(self.actors)),
            )
            await hp.start()
            self._record("hostile", victim, ev["fault"])
            await hp.dial(victim, NODE_PORT)
            self.actors.append(hp)
        elif op == "snap_join":
            await self._snap_join(ev)
        elif op == "snap_liar":
            await self._snap_join(ev, fault=ev["fault"])
        elif op == "probe":
            # Leak-gauge snapshot (the longevity soak's midpoint/end
            # markers): recorded in the trace — a probe that silently
            # vanished would void the leak comparison.
            self._record("probe", len(self.probes))
            self.probes.append(self._gauge_snapshot())
        elif op == "calm":
            # Stop every live adversary (the soak's bounded-envelope
            # closer for hostile/flood clusters).
            self._record("calm", len(self.actors))
            for actor in self.actors:
                await actor.stop()
            self.actors.clear()
        elif op == "flood":
            from p1_tpu.node.testing import FloodPlan, GreedyPeer, make_blocks

            victim = self._alive(ev["node"])
            if victim is None:
                return
            src = f"66.6.1.{len(self.actors)}"
            plan = (
                FloodPlan(queries=True, burst=4, pause_s=0.25)
                if ev["kind"] == "queries"
                else FloodPlan(blocks=True, burst=4, pause_s=0.25)
            )
            gp = GreedyPeer(
                make_blocks(4, self.difficulty),
                plan=plan,
                transport=net.net.host(src),
                rng=random.Random(net.seed * 103 + len(self.actors)),
            )
            self._record("flood", victim, ev["kind"])
            await gp.start(victim, NODE_PORT)
            self.actors.append(gp)
        elif op == "watch_start":
            await self._watch_start(ev)
        elif op == "replica_kill":
            await self._replica_kill(ev)
        elif op == "replica_join":
            await self._replica_join(ev)
        elif op == "watch_stop":
            w = self.watchers.pop(ev["watcher"], None)
            if w is not None:
                self._record("watch_stop", ev["watcher"], len(w.events))
                await w.stop()
                self.retired_watchers.append(w)
        elif op == "sub_flood":
            from p1_tpu.node.testing import FloodPlan, GreedyPeer, make_blocks

            victim = self._alive(ev["node"])
            if victim is None:
                return
            src = f"66.6.2.{len(self.actors)}"
            gp = GreedyPeer(
                make_blocks(2, self.difficulty),
                plan=FloodPlan(subscribe=True, burst=4, pause_s=0.25),
                transport=net.net.host(src),
                rng=random.Random(net.seed * 109 + len(self.actors)),
            )
            self._record("sub_flood", victim)
            await gp.start(victim, NODE_PORT)
            self.actors.append(gp)
        self.counts["applied"] += 1

    async def _watch_start(self, ev: dict) -> None:
        """Spawn one live watcher (op ``watch_start``) on the payee
        account — the wallet whose confirmations the quiesce invariant
        must prove were never missed.  Idempotent per slot (subsets of
        a schedule stay runnable); a slot freed by ``watch_stop`` may
        restart with a fresh watcher — the churn."""
        slot = ev["watcher"]
        if slot in self.watchers:
            return
        primary = self._alive(ev["node"])
        if primary is None:
            return
        w = _Watcher(
            self.net,
            serial=self.watch_serial,
            primary=primary,
            fallbacks=self.hosts[: self.n],
            item=self.payee.account,
            difficulty=self.difficulty,
            mute=self.inject_bug == "mute-push",
        )
        self.watch_serial += 1
        self.counts["watchers"] += 1
        self.watchers[slot] = w
        self._record("watch_start", primary, slot)
        await w.start()

    async def _replica_kill(self, ev: dict) -> None:
        """The directed kill-one-replica (op ``replica_kill``): crash
        the node a live watcher's ReplicaSet is actively riding —
        mid-push, which is exactly when the wallet-side failover must
        replay the cursor gap-free.  Falls back to the scheduled node
        when no watcher is live (subsets stay runnable)."""
        victim = None
        for slot in sorted(self.watchers):
            w = self.watchers[slot]
            if not w.live or w.rs.active is None:
                continue
            host = w.rs.active[0]
            if host in self.net.nodes:
                victim = host
                break
        if victim is None:
            victim = self._alive(ev["node"])
        if victim is None or victim not in self.net.nodes:
            return
        self._record("replica_kill", victim)
        await self.net.crash_node(victim, torn=0)
        self.counts["crashes"] += 1

    async def _replica_join(self, ev: dict) -> None:
        """Fleet growth (op ``replica_join``): an honest snapshot-
        bootstrapped joiner enters the mesh (the same supervised
        GETSNAPSHOT cold start ``p1 serve --bootstrap`` runs), and
        every LIVE watcher's ReplicaSet rebalances onto it — the next
        failover may land on the newcomer, which must serve the same
        commitment chain as everyone else or be demoted."""
        host = self.hosts[ev["node"]]
        await self._snap_join(ev)
        if host not in self.net.nodes:
            return  # join refused (slot taken, no peers): no rebalance
        folded = 0
        for w in self.watchers.values():
            if w.live:
                w.add_target(host)
                folded += 1
        self._record("replica_join", host, folded)

    async def _snap_join(self, ev: dict, fault: str | None = None) -> None:
        """Spawn one snapshot-syncing joiner (op ``snap_join``), or one
        joiner whose FIRST peer is a hostile snapshot server running the
        scheduled pathology (op ``snap_liar``).  Idempotent per slot so
        schedule subsets stay runnable."""
        host = self.hosts[ev["node"]]
        net = self.net
        if host in net.nodes or host in net.crashed:
            return
        peers = []
        if fault is not None:
            from p1_tpu.node.protocol import MsgType
            from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

            if fault in ("balance", "root"):
                plan = FaultPlan(snapshot_lie=fault)
            elif fault == "truncate":
                plan = FaultPlan(snapshot_chunks=1)
            else:  # "stall": a server that never answers GETSNAPSHOT
                plan = FaultPlan(swallow=frozenset({MsgType.GETSNAPSHOT}))
            src = f"66.9.0.{len(self.actors)}"
            liar = HostilePeer(
                make_blocks(
                    ev["height"], self.difficulty, miner_id=f"snapliar-{src}"
                ),
                plan=plan,
                transport=net.net.host(src),
                host=src,
                rng=random.Random(net.seed * 107 + len(self.actors)),
            )
            await liar.start()
            self.actors.append(liar)
            peers.append(f"{src}:{liar.port}")
        for p in ev.get("peers", ()):
            alive = self._alive(p)
            if alive is not None and alive not in peers:
                peers.append(alive)
        self._record("snap_join", host, fault or "honest")
        await net.add_node(
            name=host,
            peers=peers,
            snapshot_sync=True,
            snapshot_min_lead=2,
            snapshot_interval=SNAPSHOT_INTERVAL,
            recon_gossip=self.recon,
        )

    def _restore_link(self, host: str) -> None:
        if host not in self.slowed:
            return
        self.slowed.discard(host)
        self._record("restore_link", host)
        for other in self.hosts:
            if other != host:
                self.net.net.set_profile(
                    host, other, self.net.net.default_profile
                )

    async def _recover(self, host: str) -> None:
        net = self.net
        verdict = fsck_verdict(net.configs[host].store_path)
        self.recover_verdicts.append(verdict)
        if self.inject_bug == "deaf-recover":
            # Test-only seeded bug: the reboot loses its peer list.
            net.configs[host] = dataclasses.replace(
                net.configs[host], peers=()
            )
        await net.recover_node(host)
        if self.inject_bug == "relapse-disk":
            from p1_tpu.chain.testing import StoreFaultPlan

            # Test-only seeded bug: recovery declared the disk healthy
            # without proving it — the first post-recover append fails
            # and the node is stuck serve-only.
            net.stores[host].plan = StoreFaultPlan(fail_writes_from=1)
        self.counts["recoveries"] += 1

    # -- the run -----------------------------------------------------------

    async def main(self, events: list[dict]) -> dict:
        net = self.net
        violations: list[dict] = []
        # Preamble: backbone + one seeded extra edge, node 0's coinbase
        # pinned to the funded wallet, two warmup blocks everywhere.
        topo = random.Random(net.seed ^ 0x70B0C4)
        for i, host in enumerate(self.hosts[: self.n]):
            peers = []
            if i > 0:
                peers.append(self.hosts[i - 1])
                if i > 2:
                    peers.append(self.hosts[topo.randrange(i - 1)])
            kwargs = {"miner_id": self.wallet.account} if i == 0 else {}
            await net.add_node(
                name=host,
                peers=peers,
                snapshot_interval=SNAPSHOT_INTERVAL,
                recon_gossip=self.recon,
                **kwargs,
            )
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=self.wall_limit_s
        ), "chaos mesh never formed"
        miner0 = net.nodes[self.hosts[0]]
        for _ in range(2):
            await net.mine_on(miner0, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            60,
            step=0.25,
            wall_limit_s=self.wall_limit_s,
        ), "chaos mesh never converged pre-schedule"

        # The schedule, in virtual time.
        t_start = net.clock.now
        for ev in sorted(events, key=lambda e: e["at"]):
            target = t_start + ev["at"]
            if target > net.clock.now:
                await asyncio.sleep(target - net.clock.now)
            await self._apply(ev)
            self._sample_assumed()

        # Epilogue: clear EVERY fault, deterministically, then settle.
        for actor in self.actors:
            await actor.stop()
        self.actors.clear()
        for host in sorted(self.slowed):
            self._restore_link(host)
        if self.partitioned:
            self.partitioned = False
            net.net.heal()
        for host, store in sorted(net.stores.items()):
            if host in net.nodes:
                store.clear_faults()
        for host in sorted(net.crashed):
            await self._recover(host)
        faults_cleared_at = net.clock.now
        # Give the disk-recovery supervisor its backoff window: a node
        # that degraded serve-only while the fault was armed clears the
        # state one jittered retry AFTER the heal, not the same instant.
        # "Permanently stuck" means still degraded past this bound.
        await net.run_until(
            lambda: not any(
                n._store_degraded for n in net.nodes.values()
            ),
            self.settle_vs / 4,
            step=0.25,
            wall_limit_s=self.wall_limit_s,
        )
        # Two-phase settle: let post-heal sync land, then mine one
        # fresh block (the announcement that must reach EVERY node —
        # including any the schedule just rebooted, and the tie-break
        # for same-height competing tips partition mining left) and
        # require global convergence on it.
        await net.run_until(
            net.converged,
            self.settle_vs / 2,
            step=0.25,
            wall_limit_s=self.wall_limit_s,
        )
        settle_host = self._alive(0, mining=True)
        if settle_host is not None:
            await net.mine_on(net.nodes[settle_host])
        converged = await net.run_until(
            lambda: net.converged()
            and len(set(net.heights())) == 1
            # Snapshot joiners owe a finished verdict: ASSUMED must have
            # resolved — flip or quarantine+fallback — by quiesce.
            and all(
                n.validation_state == "validated"
                for n in net.nodes.values()
            ),
            self.settle_vs / 2,
            step=0.25,
            wall_limit_s=self.wall_limit_s,
        )
        settle_vs = net.clock.now - faults_cleared_at
        # Push-plane quiesce: the settle block above was pushed to every
        # live subscription — give every surviving watcher the window to
        # verify its way (failovers and gap replays included) to the
        # converged tip before judging its stream.
        if converged and self.watchers:
            tip_h = max(net.heights())
            await net.run_until(
                lambda: all(
                    # Zero events = the watch TOFU-anchored AT the
                    # converged tip (a late start racing the settle
                    # block): caught up by definition.
                    not w.live or w.tip_height < 0 or w.tip_height >= tip_h
                    for w in self.watchers.values()
                ),
                self.settle_vs / 2,
                step=0.25,
                wall_limit_s=self.wall_limit_s,
            )

        # -- the invariant suite, at quiesce -------------------------------
        if not converged:
            tips = {h: n.chain.tip_hash.hex()[:12] for h, n in net.nodes.items()}
            violations.append(
                {
                    "invariant": "converge",
                    "detail": f"tips still split {settle_vs:.1f}vs after "
                    f"the last fault cleared: {tips}",
                }
            )
        if not net.ledger_conserved():
            violations.append(
                {
                    "invariant": "ledger",
                    "detail": "ledger sum != BLOCK_REWARD * height somewhere",
                }
            )
        for host, node in net.nodes.items():
            if node._store_degraded:
                violations.append(
                    {
                        "invariant": "serve-only",
                        "detail": f"{host} still degraded serve-only after "
                        "its disk healed",
                    }
                )
        for verdict in self.recover_verdicts:
            if verdict > 1:
                violations.append(
                    {
                        "invariant": "fsck",
                        "detail": "a crashed store was unrecoverable "
                        "(verdict 2) at reboot",
                    }
                )
        for host, node in net.nodes.items():
            if node.validation_state != "validated":
                violations.append(
                    {
                        "invariant": "assumed",
                        "detail": f"{host} still in the ASSUMED state at "
                        "quiesce (revalidation never resolved)",
                    }
                )
        violations.extend(self._check_pools())
        violations.extend(self._check_caches())
        violations.extend(self._check_assumed_samples())
        violations.extend(self._check_watchers(converged))
        violations.extend(self._check_leaks())
        all_watchers = self.retired_watchers + list(self.watchers.values())
        for w in self.watchers.values():
            await w.stop()

        from p1_tpu.node.telemetry import propagation_summary_ms

        heights = net.heights()
        report = {
            "events": len(events),
            "schedule_tail": [e["op"] for e in events][-6:],
            **self.counts,
            "recover_verdicts": self.recover_verdicts,
            "virtual_s": round(net.clock.now, 3),
            "net_events": net.net.events,
            "probes": len(self.probes),
            # The raw leak-gauge snapshots (midpoint vs end): the
            # numbers behind any "leak" violation, kept in the report
            # so a failing soak is diagnosable from its JSON alone.
            "leak_gauges": {
                "mid": self.probes[-2] if len(self.probes) >= 2 else None,
                "end": self.probes[-1] if self.probes else None,
            },
            "settle_virtual_s": round(settle_vs, 3),
            "watch_events": sum(len(w.events) for w in all_watchers),
            "watch_resets": sum(w.resets for w in all_watchers),
            "heights": {"min": min(heights), "max": max(heights)},
            "reorgs_total": sum(
                n.metrics.reorgs for n in net.nodes.values()
            ),
            # Telemetry timeline (round 14): survivor-side propagation
            # latency under the whole fault schedule, virtual-time —
            # the "how did gossip feel while the mesh burned" figure a
            # convergence bit cannot carry.
            "telemetry": {
                "propagation": propagation_summary_ms(
                    n.telemetry for n in net.nodes.values()
                )
            },
            "violations": violations,
        }
        await net.stop_all()
        # Shutdown verdicts AFTER the stores closed cleanly: whatever
        # the schedule inflicted, what reaches disk must stay loadable.
        for host in self.hosts:
            config = net.configs.get(host)
            if config is None:
                continue  # a joiner slot this schedule never spawned
            path = config.store_path
            if path and fsck_verdict(path) > 1:
                report["violations"].append(
                    {
                        "invariant": "fsck",
                        "detail": f"{host}'s store unrecoverable at shutdown",
                    }
                )
        report["trace_digest"] = net.trace_digest()
        return report

    def _gauge_snapshot(self) -> dict:
        """Per-node leak gauges: everything that must NOT grow
        monotonically over a long, stationary fault mix.  Pure reads —
        a probe must never perturb what it measures."""
        out: dict[str, dict] = {}
        for host in self.hosts:
            node = self.net.nodes.get(host)
            if node is None:
                continue
            out[host] = {
                "tasks": len(node._tasks) + len(node._sessions),
                "banned": len(node._banned_until),
                "violations": len(node._violations),
                "known_addrs": len(node._known_addrs),
                "tried_addrs": len(node._tried_addrs),
                "mempool": len(node.mempool),
                "sig_cache": len(node.sig_cache),
                "subs_live": node.subscriptions.snapshot()["live"],
                "subs_queue_bytes": node.subscriptions.queue_depth_bytes,
                "gauge_bytes": node._memory_gauge(),
                # Supervision/store retry counters: monotone by design —
                # the leak check bounds their second-half GROWTH, not
                # their value (a runaway retry loop is a hockey stick
                # even when every table above stays capped).  Liveness
                # pings are deliberately NOT in here: their rate rides
                # topology and gossip idleness, not retry health.
                "retry_counters": int(
                    node.metrics.sync_stalls
                    + node.metrics.sync_failovers
                    + node.metrics.sync_exhausted
                    + node.metrics.store_retries
                    + node.metrics.mempool_sync_stalls
                    + node.metrics.cblock_fetch_stalls
                ),
            }
        return out

    def _check_leaks(self) -> list[dict]:
        """The longevity invariants: hard caps on every bounded table
        at quiesce, plus mid-vs-end growth comparisons from the probe
        snapshots.  Active only when a schedule carried probes (the
        soak always does); a plain chaos schedule skips it."""
        from p1_tpu.node.node import (
            MAX_KNOWN_ADDRS,
            MAX_PEERS,
            MAX_TRACKED_HOSTS,
            MAX_TRIED_ADDRS,
        )

        out: list[dict] = []
        if len(self.probes) < 2:
            return out
        mid, end = self.probes[-2], self.probes[-1]
        for host in self.hosts:
            node = self.net.nodes.get(host)
            if node is None:
                continue
            caps = [
                ("banned", len(node._banned_until), MAX_TRACKED_HOSTS),
                ("violations", len(node._violations), MAX_TRACKED_HOSTS),
                ("known_addrs", len(node._known_addrs), MAX_KNOWN_ADDRS),
                ("tried_addrs", len(node._tried_addrs), MAX_TRIED_ADDRS),
                ("sig_cache", len(node.sig_cache), node.sig_cache.max_entries),
                (
                    "proof_cache_bytes",
                    node.chain.proof_cache.bytes_used,
                    node.chain.proof_cache.max_bytes,
                ),
                (
                    "filter_index_bytes",
                    node.chain.filter_index.bytes_used,
                    node.chain.filter_index.max_bytes,
                ),
                ("tasks", len(node._tasks) + len(node._sessions),
                 MAX_PEERS + 16),
            ]
            for name, value, cap in caps:
                if value > cap:
                    out.append(
                        {
                            "invariant": "leak",
                            "detail": f"{host} {name} = {value} over its "
                            f"bound {cap} at quiesce",
                        }
                    )
            m, e = mid.get(host), end.get(host)
            if m is None or e is None:
                continue  # crashed across a probe: growth unreadable
            if e["tasks"] > m["tasks"] + 8:
                out.append(
                    {
                        "invariant": "leak",
                        "detail": f"{host} task count grew {m['tasks']} -> "
                        f"{e['tasks']} over the second half",
                    }
                )
            if e["mempool"] > m["mempool"] + 64:
                out.append(
                    {
                        "invariant": "leak",
                        "detail": f"{host} mempool grew {m['mempool']} -> "
                        f"{e['mempool']} over the second half",
                    }
                )
            growth = e["retry_counters"] - m["retry_counters"]
            # A crash between the probes resets the node's counters
            # (recover builds a fresh Node): negative growth means a
            # restart, not a recovery of leaked memory — skip.
            if growth > 3 * m["retry_counters"] + 100:
                out.append(
                    {
                        "invariant": "leak",
                        "detail": f"{host} supervision/retry counters grew "
                        f"{growth} in the second half vs "
                        f"{m['retry_counters']} in the first — a runaway "
                        "retry loop",
                    }
                )
        return out

    def _sample_assumed(self) -> None:
        """Record every ASSUMED joiner's answer to "what is the wallet's
        balance at your tip?" — the claims the flip must never have let
        a fully-validated node contradict."""
        for host in self.joiner_hosts:
            node = self.net.nodes.get(host)
            if node is None or node.validation_state != "assumed":
                continue
            self.samples.append(
                (
                    host,
                    node.chain.height,
                    node.chain.tip_hash,
                    node.chain.balance(self.wallet.account),
                )
            )

    def _check_assumed_samples(self) -> list[dict]:
        """The snapshot invariant: for every joiner that FLIPPED (its
        snapshot was confirmed honest), every balance it reported while
        ASSUMED must match what the validated history says at the same
        block.  Joiners that diverged made no claim that survived — the
        quarantine retracted their state wholesale."""
        from p1_tpu.chain.ledger import balances as audit_balances

        out = []
        account = self.wallet.account
        for host, height, tip_hash, reported in self.samples:
            node = self.net.nodes.get(host)
            if node is None or node.metrics.snapshot_flips == 0:
                continue
            for ref_host, ref in self.net.nodes.items():
                if ref_host == host or ref.chain.base_height != 0:
                    continue
                if ref.chain.main_hash_at(height) != tip_hash:
                    continue  # sampled tip reorged away: no surviving claim
                blocks = [
                    ref.chain._block_at(ref.chain.main_hash_at(h))
                    for h in range(height + 1)
                ]
                truth = audit_balances(blocks).get(account, 0)
                if truth != reported:
                    out.append(
                        {
                            "invariant": "assumed-balance",
                            "detail": f"{host} reported {reported} for the "
                            f"wallet at height {height} while ASSUMED; the "
                            f"validated chain says {truth}",
                        }
                    )
                break
        return out

    def _check_watchers(self, converged: bool) -> list[dict]:
        """The push-plane invariants at quiesce (round 21).

        Every watcher STILL LIVE at the horizon owes the tentpole
        claim: its verified stream is gap-free from its coverage floor
        to the converged tip, byte-agrees with the converged chain
        (block hash AND filter-header commitment per height), and holds
        a matched event carrying the paying txids for EVERY height the
        watched wallet was paid — zero missed confirmations, whatever
        the schedule did to the serving nodes.  Churned-away watchers
        are judged for honesty only: the mesh tells no lies, so a
        watch that ended in a CommitmentViolation demoted an honest
        node — itself a bug.  And no node may hold more live
        subscription entries than there are live watchers: a dead
        session whose registry entry survived is a leak."""
        out: list[dict] = []
        all_watchers = self.retired_watchers + list(self.watchers.values())
        for w in all_watchers:
            if w.error is not None and "CommitmentViolation" in w.error:
                out.append(
                    {
                        "invariant": "push-honest",
                        "detail": f"watcher {w.serial} convicted an honest "
                        f"mesh of lying: {w.error}",
                    }
                )
        if not converged or not self.watchers:
            return out
        live_watchers = sum(1 for w in self.watchers.values() if w.live)
        subs_live = sum(
            n.subscriptions.snapshot()["live"]
            for n in self.net.nodes.values()
        )
        if subs_live > live_watchers:
            out.append(
                {
                    "invariant": "push-leak",
                    "detail": f"{subs_live} live subscription entries for "
                    f"{live_watchers} live watchers at quiesce — dead "
                    "sessions left registry entries behind",
                }
            )
        # The converged truth, from an archive-grade node (full blocks
        # from genesis); the generators cap pruning/re-basing at one
        # host per schedule, so one nearly always exists — without one
        # the deep replay below has no ground truth and is skipped.
        ref = next(
            (
                n
                for n in self.net.nodes.values()
                if n.chain.base_height == 0 and not n.chain.prune_floor
            ),
            None,
        )
        if ref is None:
            return out
        chain = ref.chain
        tip_h = chain.height
        account = self.payee.account
        paid: dict[int, set[bytes]] = {}
        for h in range(1, tip_h + 1):
            blk = chain._block_at(chain.main_hash_at(h))
            ids = {
                tx.txid()
                for tx in blk.txs
                if account in (tx.sender, tx.recipient)
            }
            if ids:
                paid[h] = ids
        for slot, w in sorted(self.watchers.items()):
            if not w.live:
                out.append(
                    {
                        "invariant": "push-live",
                        "detail": f"watcher {slot} died mid-watch: {w.error}",
                    }
                )
                continue
            if w.tip_height < 0:
                # Zero events: the watch TOFU-anchored at the converged
                # tip (a late start racing the settle block), so there
                # was nothing to push and nothing to judge — its floor
                # is unset, which also skips the per-height checks.
                continue
            if w.tip_height < tip_h:
                out.append(
                    {
                        "invariant": "push-lag",
                        "detail": f"watcher {slot} stuck at height "
                        f"{w.tip_height} with the mesh converged at {tip_h}",
                    }
                )
                continue
            lo = w.floor if w.floor is not None else tip_h
            for h in range(lo + 1, tip_h + 1):
                ev = w.by_height.get(h)
                if ev is None:
                    out.append(
                        {
                            "invariant": "push-gap",
                            "detail": f"watcher {slot} has no event for "
                            f"height {h} inside its verified window",
                        }
                    )
                elif ev["block_hash"] != chain.main_hash_at(h):
                    out.append(
                        {
                            "invariant": "push-chain",
                            "detail": f"watcher {slot}'s last event at "
                            f"height {h} is not the converged block",
                        }
                    )
                elif ev["filter_header"] != chain.filter_headers.header_at(h):
                    out.append(
                        {
                            "invariant": "push-commit",
                            "detail": f"watcher {slot}'s filter header at "
                            f"height {h} contradicts the converged "
                            "commitment chain",
                        }
                    )
                elif h in paid and (
                    not ev["matched"] or not paid[h] <= set(ev["txids"])
                ):
                    out.append(
                        {
                            "invariant": "push-missed",
                            "detail": f"watcher {slot} missed the wallet's "
                            f"confirmation at height {h} "
                            f"(matched={ev['matched']})",
                        }
                    )
        return out

    def _check_pools(self) -> list[dict]:
        """No crash-restart (or reorg) may resurrect a transaction the
        node's own main chain already mined — the mempool
        crash-consistency invariant."""
        out = []
        for host, node in self.net.nodes.items():
            for txid in node.mempool._txs:
                if txid in node.chain._tx_index:
                    out.append(
                        {
                            "invariant": "resurrect",
                            "detail": f"{host} pool holds mined tx "
                            f"{txid.hex()[:16]}",
                        }
                    )
        return out

    def _check_caches(self) -> list[dict]:
        """Proof/filter caches must agree with the post-reorg chain:
        every resident filter byte-matches a fresh build from the block
        body, and the tip block's transaction proofs verify as a
        stateless client would."""
        from p1_tpu.chain.filters import block_filter
        from p1_tpu.chain.proof import SPVError, verify_tx_proof

        out = []
        for host, node in self.net.nodes.items():
            chain = node.chain
            tip = chain.tip
            # sorted: the dedup set must not pick the probe order, or
            # the violation list (and any repro built from it) rides
            # hash order — the exact class `p1 lint`'s set-iteration
            # rule pins.
            for height in sorted({1, chain.height // 2, chain.height}):
                bhash = chain.main_hash_at(height)
                if bhash is None:
                    continue
                cached = chain.filter_index.get(bhash)
                if cached is not None and cached != block_filter(
                    chain._block_at(bhash)
                ):
                    out.append(
                        {
                            "invariant": "caches",
                            "detail": f"{host} filter for height {height} "
                            "diverges from its block",
                        }
                    )
            for tx in tip.txs[:2]:
                proof = chain.tx_proof(tx.txid())
                try:
                    if proof is None:
                        raise SPVError("no proof for a tip transaction")
                    verify_tx_proof(
                        proof,
                        self.difficulty,
                        chain.genesis.block_hash(),
                        txid=tx.txid(),
                    )
                    if proof.height != chain.height:
                        raise SPVError("tip proof at wrong height")
                except SPVError as e:
                    out.append(
                        {
                            "invariant": "caches",
                            "detail": f"{host} tip proof failed: {e}",
                        }
                    )
        return out


# -- delta-debugging shrinker ---------------------------------------------


def shrink_schedule(
    events: list[dict], reproduces, max_runs: int = 120
) -> tuple[list[dict], int]:
    """Minimize ``events`` to a small list that still ``reproduces``
    (ddmin: try dropping chunks at doubling granularity, restart
    coarse after every success).  ``reproduces(subset) -> bool`` runs
    one full chaos replay per call, so ``max_runs`` bounds total cost;
    the result is 1-minimal when the budget allows (no single event can
    be removed), merely smaller when it doesn't."""
    assert reproduces(events), "the full schedule must reproduce first"
    runs = 1
    n = 2
    while len(events) >= 2 and runs < max_runs:
        chunk = max(1, len(events) // n)
        reduced = False
        for start in range(0, len(events), chunk):
            candidate = events[:start] + events[start + chunk :]
            if not candidate:
                continue
            runs += 1
            if reproduces(candidate):
                events = candidate
                n = max(n - 1, 2)
                reduced = True
                break
            if runs >= max_runs:
                break
        if not reduced:
            if n >= len(events):
                break
            n = min(len(events), 2 * n)
    return events, runs


# -- repro artifacts -------------------------------------------------------


def write_repro(
    path,
    report: dict,
    events: list[dict],
    *,
    seed: int,
    nodes: int,
    difficulty: int,
    inject_bug: str | None = None,
) -> None:
    """One replayable violation: everything ``run_repro`` needs to
    reproduce it from nothing — seed, topology size, the (shrunk)
    schedule, the expected violations and trace digest."""
    artifact = {
        "format": REPRO_FORMAT,
        "seed": seed,
        "nodes": nodes,
        "difficulty": difficulty,
        "inject_bug": inject_bug,
        "events": events,
        "expected_violations": sorted(
            {v["invariant"] for v in report["violations"]}
        ),
        "expected_trace_digest": report["trace_digest"],
    }
    Path(path).write_text(json.dumps(artifact, indent=1))


def run_repro(path) -> tuple[dict, dict]:
    """Replay a repro artifact; returns ``(report, artifact)``.
    Raises ValueError for anything that is not a chaos repro."""
    try:
        artifact = json.loads(Path(path).read_text())
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable repro artifact {path}: {e}") from None
    if not isinstance(artifact, dict) or artifact.get("format") != REPRO_FORMAT:
        raise ValueError(f"{path} is not a {REPRO_FORMAT} artifact")
    report = run_chaos(
        artifact["seed"],
        nodes=artifact["nodes"],
        events=artifact["events"],
        difficulty=artifact["difficulty"],
        inject_bug=artifact.get("inject_bug"),
    )
    return report, artifact
