"""Set-reconciliation codec for bandwidth-scale tx relay (Erlay analog).

Flooding announces every transaction on every link — O(links) bytes
for the mesh.  Erlay (Naumenko et al., the Bitcoin lineage this
codebase already credits for compact blocks/BIP157) cuts that to
O(nodes): each peer pair periodically exchanges a fixed-size *sketch*
of the short IDs it would have flooded, and the symmetric difference
decodes from the XOR of the two sketches — bytes proportional to the
DIFFERENCE, not to the sets.

The sketch is minisketch-style (PinSketch over GF(2^32)): for a set
``S`` of nonzero 32-bit elements and capacity ``c``, the sketch is the
odd power sums ``s_k = sum(m^k for m in S)`` for ``k = 1, 3, ...,
2c-1`` — ``4c`` bytes regardless of ``|S|``.  Addition in GF(2^m) is
XOR, so the sketch of a symmetric difference is the XOR of the
sketches, and any difference of up to ``c`` elements decodes exactly:

- even syndromes come free from Frobenius (``s_{2k} = s_k^2``), so the
  syndromes Berlekamp–Massey needs cost only the odd wire words;
- BM yields the connection polynomial whose reversal has the
  difference elements as roots;
- roots are recovered WITHOUT a Chien sweep (2^32 candidates is not a
  pure-Python option): the polynomial must split into distinct linear
  factors over the field (checked via ``x^(2^32) == x`` mod the
  polynomial), then Berlekamp's trace construction splits it
  recursively along the 32 trace coordinates;
- over-capacity failure is DETECTED, not mis-decoded: raw PinSketch
  will happily hallucinate a small set whose first syndromes match an
  over-full sketch (the derived even syndromes verify nothing — they
  are Frobenius images for ANY set), so every sketch carries one extra
  RESERVED syndrome beyond its claimed capacity.  A genuine ≤capacity
  difference satisfies it automatically; a spurious solution must also
  match an independent 32-bit word it was never fitted to, so a
  difference beyond capacity returns None except with probability
  2^-32 per round — the same odds Erlay accepts for a short-ID
  collision.  The recovered set is additionally re-sketched and must
  reproduce the input byte-for-byte.  Callers fall back to flood on
  None.

Short IDs are salted per peer pair (both HELLO instance nonces, order-
independent), so an adversary cannot precompute colliding txids for
links it is not on; a collision on one link costs one tx one round on
that link only.

Everything here is a pure function of bytes — no clock, no RNG, no IO
— and carries ZERO analysis-allowlist grants (the chain/snapshot.py
discipline).  Pure Python first, by design: sets are per-link pending
windows (tens of elements) and capacity is clamped at
``MAX_CAPACITY``, so the field work is thousands of 32-bit carryless
multiplies per round.  If profiling ever says this is hot, the seam
for a native build is this module's public surface (``sketch`` /
``combine`` / ``decode`` are byte-in/byte-out, the same boundary
minisketch's C library exposes) — mirror the ``hashx/native``
wheel > ctypes > pure ladder, do not inline field ops elsewhere.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "MAX_CAPACITY",
    "pair_salt",
    "short_id",
    "sketch",
    "combine",
    "decode",
    "estimate_capacity",
]

#: Hard ceiling on sketch capacity: bounds both the wire frame (4c
#: bytes) and the decode work an adversarial SKETCH can demand.
MAX_CAPACITY = 64

#: GF(2^32) reduction polynomial x^32 + x^7 + x^3 + x^2 + 1 (the same
#: modulus minisketch uses for 32-bit fields).
_MOD = (1 << 32) | 0x8D
_MASK = (1 << 32) - 1
_ORDER = (1 << 32) - 1  # multiplicative group order


def _gmul(a: int, b: int) -> int:
    """Carryless multiply in GF(2^32), reduced."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a >> 32:
            a ^= _MOD
    return r


def _gsqr(a: int) -> int:
    return _gmul(a, a)


def _gpow(a: int, e: int) -> int:
    r = 1
    while e:
        if e & 1:
            r = _gmul(r, a)
        a = _gmul(a, a)
        e >>= 1
    return r


def _ginv(a: int) -> int:
    assert a, "zero has no inverse"
    return _gpow(a, _ORDER - 1)


# -- salted short IDs ------------------------------------------------------


def pair_salt(nonce_a: int, nonce_b: int) -> bytes:
    """The per-link salt: order-independent over the two HELLO instance
    nonces, so both endpoints derive the same value and no third party
    shares it with any other link."""
    lo, hi = sorted((nonce_a, nonce_b))
    return hashlib.sha256(
        b"p1-recon-salt" + lo.to_bytes(8, "big") + hi.to_bytes(8, "big")
    ).digest()[:16]


def short_id(salt: bytes, txid: bytes) -> int:
    """32-bit salted short ID for a txid, never zero (zero is the
    sketch's additive identity and cannot be an element)."""
    sid = int.from_bytes(hashlib.sha256(salt + txid).digest()[:4], "big")
    return sid if sid else 0x811C9DC5


# -- sketch construction ---------------------------------------------------


def sketch(ids, capacity: int) -> bytes:
    """Serialize the odd power-sum syndromes of ``ids`` at ``capacity``.

    ``4 * (capacity + 1)`` bytes, independent of ``len(ids)`` — the +1
    is the reserved verification syndrome (module docstring).  Byte-
    identical for identical sets (order-free: XOR accumulation
    commutes).
    """
    if not 1 <= capacity <= MAX_CAPACITY:
        raise ValueError(f"capacity {capacity} outside 1..{MAX_CAPACITY}")
    syn = [0] * (capacity + 1)
    for m in ids:
        if not 0 < m <= _MASK:
            raise ValueError(f"element {m} outside GF(2^32)*")
        p = m
        m2 = _gmul(m, m)
        for i in range(capacity + 1):
            syn[i] ^= p
            p = _gmul(p, m2)
    return b"".join(s.to_bytes(4, "big") for s in syn)


def combine(a: bytes, b: bytes) -> bytes:
    """XOR two equal-capacity sketches: the sketch of the symmetric
    difference of the underlying sets."""
    if len(a) != len(b) or len(a) % 4:
        raise ValueError("sketch length mismatch")
    return bytes(x ^ y for x, y in zip(a, b))


def estimate_capacity(local_size: int, remote_size: int) -> int:
    """Capacity guess for a round over two PENDING QUEUES, clamped to
    the frame bound.

    Erlay's estimator is ``|ls - rs| + q*min + c`` because it
    reconciles whole announcement sets that mostly OVERLAP.  This
    protocol reconciles per-link pending queues, and two ends' queues
    are mostly DISJOINT — each side queued precisely what it believes
    the other lacks — so the expected difference is ``ls + rs``, and
    the subtraction heuristic under-sizes the sketch catastrophically
    (measured: a mesh-wide storm failed ~20% of rounds before this was
    a sum).  Overlap only ever makes the true difference SMALLER than
    the estimate, which decoding handles for free; underestimates fail
    detectably and fall back to flood."""
    d = local_size + remote_size + 2
    return max(1, min(d, MAX_CAPACITY))


def capacity_of(data: bytes) -> int:
    """The claimed capacity of a serialized sketch (word count minus
    the reserved verification syndrome)."""
    return len(data) // 4 - 1


# -- decoding --------------------------------------------------------------
#
# Polynomials over GF(2^32) are lists of coefficients, index = degree.


def _ptrim(p: list) -> list:
    while p and p[-1] == 0:
        p.pop()
    return p


def _pmod(a: list, b: list) -> list:
    """a mod b, b monic-normalized inside."""
    a = a[:]
    inv = _ginv(b[-1])
    while len(a) >= len(b):
        c = _gmul(a[-1], inv)
        if c:
            off = len(a) - len(b)
            for i, bv in enumerate(b):
                a[off + i] ^= _gmul(c, bv)
        a.pop()
    return _ptrim(a)


def _pdiv(a: list, b: list) -> list:
    """a // b (exact or not; remainder discarded)."""
    a = a[:]
    q = [0] * max(1, len(a) - len(b) + 1)
    inv = _ginv(b[-1])
    while len(a) >= len(b):
        c = _gmul(a[-1], inv)
        off = len(a) - len(b)
        q[off] = c
        if c:
            for i, bv in enumerate(b):
                a[off + i] ^= _gmul(c, bv)
        a.pop()
    return _ptrim(q)


def _pgcd(a: list, b: list) -> list:
    while b:
        a, b = b, _pmod(a, b)
    return a


def _psqr_mod(p: list, m: list) -> list:
    """p^2 mod m via Frobenius: squaring is coefficient-wise square
    spread to even degrees (char 2)."""
    sq = [0] * (2 * len(p) - 1) if p else []
    for i, c in enumerate(p):
        if c:
            sq[2 * i] = _gsqr(c)
    return _pmod(sq, m)


def _monic(p: list) -> list:
    inv = _ginv(p[-1])
    return [_gmul(c, inv) for c in p]


def _berlekamp_massey(s: list) -> list:
    """Connection polynomial C (C[0] == 1) of the syndrome sequence."""
    C, B = [1], [1]
    L, m, b = 0, 1, 1
    for n, sn in enumerate(s):
        d = sn
        for i in range(1, L + 1):
            if i < len(C) and C[i]:
                d ^= _gmul(C[i], s[n - i])
        if d == 0:
            m += 1
            continue
        coef = _gmul(d, _ginv(b))
        if 2 * L <= n:
            T = C[:]
            if len(C) < len(B) + m:
                C = C + [0] * (len(B) + m - len(C))
            for i, bv in enumerate(B):
                if bv:
                    C[i + m] ^= _gmul(coef, bv)
            L, B, b, m = n + 1 - L, T, d, 1
        else:
            if len(C) < len(B) + m:
                C = C + [0] * (len(B) + m - len(C))
            for i, bv in enumerate(B):
                if bv:
                    C[i + m] ^= _gmul(coef, bv)
            m += 1
    return _ptrim(C)


def _roots(p: list) -> list | None:
    """All roots of monic ``p``, or None unless ``p`` is a product of
    DISTINCT linear factors over GF(2^32) (anything else means the
    sketch was over capacity or garbage).  Berlekamp trace splitting:
    ``Tr(beta*x)`` takes values 0/1 on the field, so its gcd with ``p``
    separates the roots along each of the 32 trace coordinates; distinct
    roots differ in at least one coordinate, so recursion terminates.

    The basis cursor is PER FACTOR, resumed from the split that made
    it, not shared across the stack: a beta that fails to split ``q``
    has constant trace on ``q``'s roots, hence on every DESCENDANT of
    ``q`` — but says nothing about ``q``'s siblings, whose roots it may
    be the only coordinate separating.  (A shared monotonic cursor
    looked equivalent and decoded every small sketch; it starts losing
    real ≥20-element differences once the recursion tree is deep
    enough for a sibling to need an already-consumed coordinate.)
    """
    # Distinct-linear check: x^(2^32) == x mod p.
    t = [0, 1] if len(p) > 2 else _pmod([0, 1], p)
    frob = t[:]
    for _ in range(32):
        frob = _psqr_mod(frob, p)
    if _ptrim([a ^ b for a, b in zip(frob + [0] * len(t), t + [0] * len(frob))]):
        return None
    out: list = []
    stack = [(p, 0)]
    while stack:
        q, basis = stack.pop()
        if len(q) == 2:  # monic x + a -> root a
            out.append(q[0])
            continue
        split = None
        while split is None:
            if basis >= 32:
                return None  # cannot happen for distinct roots
            beta = 1 << basis
            basis += 1
            term = _pmod([0, beta], q)
            acc = term[:]
            for _ in range(31):
                term = _psqr_mod(term, q)
                acc = _ptrim(
                    [
                        a ^ b
                        for a, b in zip(
                            acc + [0] * len(term), term + [0] * len(acc)
                        )
                    ]
                )
            g = _pgcd(q[:], acc)
            if g and 1 < len(g) < len(q):
                split = (_monic(g), _monic(_pdiv(q, g)))
        stack.append((split[0], basis))
        stack.append((split[1], basis))
    return out


def decode(data: bytes) -> tuple | None:
    """Decode a (combined) sketch into its element set.

    Returns a sorted tuple of the symmetric-difference elements, or
    None when the difference exceeded the sketch's capacity or the
    bytes are not a valid sketch — the caller's signal to fall back to
    flood.  Success is PROVEN, not assumed: the connection polynomial
    must also generate the reserved syndrome it was never fitted to,
    and the recovered set is re-sketched and must reproduce the input
    byte-for-byte.
    """
    if len(data) < 8 or len(data) % 4 or len(data) > 4 * (MAX_CAPACITY + 1):
        return None
    words = len(data) // 4
    cap = words - 1  # last odd syndrome is the verification reserve
    odd = [int.from_bytes(data[i : i + 4], "big") for i in range(0, len(data), 4)]
    if not any(odd):
        return ()
    # Full syndrome run s_1..s_{2*words}: odd given, even from Frobenius.
    syn = [0] * (2 * words + 1)
    for k in range(words):
        syn[2 * k + 1] = odd[k]
    for k in range(1, words + 1):
        syn[2 * k] = _gsqr(syn[k])
    C = _berlekamp_massey(syn[1:])
    deg = len(C) - 1
    if deg < 1 or deg > cap or C[-1] == 0 or C[0] != 1:
        return None
    # Roots of the reversal x^deg * C(1/x) are the elements themselves.
    rev = _monic(C[::-1])
    roots = _roots(rev)
    if roots is None or len(roots) != deg or 0 in roots:
        return None
    elems = tuple(sorted(roots))
    if len(set(elems)) != deg:
        return None
    # The proof: re-sketching must reproduce the input exactly.
    if sketch(elems, cap) != data:
        return None
    return elems
