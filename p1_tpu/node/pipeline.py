"""Staged block pipeline: off-loop validate/store lanes (ROADMAP item 1).

The node's block lifecycle is five stages — wire framing → admission →
validation → store → relay.  Framing, admission, and relay are pure
event-loop work (parse a length-prefixed frame, charge a token bucket,
fan a payload out to peer write queues) and stay on the loop.  The two
CPU/IO-heavy stages move here:

- **validate**: batched Ed25519 pre-verification through the native
  engine (core/keys.py).  The ctypes bridge releases the GIL inside the
  C++ core, so a single lane thread driving ``preverify_signatures``
  gets real multi-core parallelism from the verify worker pool
  (``keys.verify_workers`` is the sizing knob — this module only moves
  the *call site* off the loop).
- **store**: every granted fsync chain — append, batch-close sync,
  prune-base sidecar flips, mempool/addr checkpoints, snapshot flips —
  runs on a dedicated single-thread writer lane.  One thread owns the
  flocked append fd, so the store's single-writer discipline and append
  ordering survive unchanged (the lane's queue IS the append order).

``workers == 0`` (the default) disables staging: ``run_validate`` /
``run_store`` call the function inline with **no awaits**, so the
scheduling behavior is byte-identical to the historical inline node.
``workers >= 1`` submits through ``loop.run_in_executor``.  Under the
network simulator this is STILL synchronous — ``SimLoop.run_in_executor``
resolves the future inline (netsim.py) — which is what makes the sim
trace digest byte-identical with staging on or off at 1 worker: the
determinism proof is by construction, not by test luck.

Hand-off is zero-copy: stage functions receive the same ``bytes`` /
``memoryview`` objects the wire frame decoded into (the packed plane
never re-encodes between stages); ``nbytes`` only *accounts* those
buffers against the governor gauge while a job is in flight, it never
copies them.

Supervision: a lane worker that dies mid-job (the chaos injector's
``fail_next`` seam, or a pool whose thread was torn down under it)
raises ``WorkerCrash``; the pipeline respawns the lane's pool, counts
the respawn, and retries the job once — mirroring the node task
supervisor's crash-count-and-restart lineage (NodeMetrics.task_crashes).
"""

from __future__ import annotations

import concurrent.futures
import threading
from concurrent.futures import ThreadPoolExecutor

import asyncio

STAGES = ("frame", "admission", "validate", "store", "relay")

#: Stages with an off-loop lane (the other three live on the event loop
#: and can only "crash" by the whole process dying — the chaos injector
#: maps those stage-crash events to process crashes).
LANE_STAGES = ("validate", "store")


class WorkerCrash(RuntimeError):
    """A pipeline lane worker died mid-job (injected or real)."""


class _Lane:
    """One off-loop stage: a single-thread pool plus depth accounting.

    ``max_workers=1`` is a correctness choice, not a tuning default: the
    lane's FIFO queue is what preserves per-peer arrival order through
    the validate stage and append order through the store stage.
    Parallelism comes from *inside* the jobs (the verify pool fans one
    preverify batch across cores), never from concurrent lane jobs.
    """

    def __init__(self, name: str, workers: int):
        self.name = name
        self.workers = workers
        self.pool: ThreadPoolExecutor | None = (
            self._make_pool() if workers > 0 else None
        )
        self.depth = 0  # jobs submitted and not yet finished
        self.queued_bytes = 0  # payload bytes those jobs reference
        self.jobs = 0  # lifetime jobs (telemetry)
        self.respawns = 0  # worker deaths survived
        self.fail_next = False  # chaos seam: next job dies
        self.alive = True

    def _make_pool(self) -> ThreadPoolExecutor:
        return ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"p1-{self.name}"
        )

    def respawn(self) -> None:
        if self.pool is not None:
            # wait=False: the dead worker has nothing left to run, and
            # the respawn happens on the event loop — never block it.
            self.pool.shutdown(wait=False)
        if self.workers > 0:
            self.pool = self._make_pool()
        self.respawns += 1
        self.alive = True


class NodePipeline:
    """Validate/store lanes with governor-visible depth accounting.

    The node owns exactly one; stages call ``run_validate`` /
    ``run_store`` with a plain synchronous function and its arguments.
    The function runs off-loop when staging is on, inline when off —
    callers never branch on the mode.
    """

    def __init__(self, workers: int = 0, on_respawn=None):
        self.workers = workers
        self._lanes = {name: _Lane(name, workers) for name in LANE_STAGES}
        #: Called with the lane name after a worker respawn (the node
        #: wires this to NodeMetrics so crashes are counted, per the
        #: task-supervisor lineage).
        self.on_respawn = on_respawn
        # Guards respawn against the (loop thread, lane thread) pair
        # both observing a broken pool; cheap and uncontended.
        self._respawn_lock = threading.Lock()

    # -- introspection ------------------------------------------------

    @property
    def staged(self) -> bool:
        return self.workers > 0

    @property
    def queued_bytes(self) -> int:
        """Bytes referenced by in-flight lane jobs (governor gauge)."""
        return sum(lane.queued_bytes for lane in self._lanes.values())

    def depths(self) -> dict[str, int]:
        return {name: lane.depth for name, lane in self._lanes.items()}

    def status(self) -> dict:
        """The ``status()["pipeline"]`` block: depths + worker liveness."""
        return {
            "workers": self.workers,
            "validate_depth": self._lanes["validate"].depth,
            "store_depth": self._lanes["store"].depth,
            "queued_bytes": self.queued_bytes,
            "validate_alive": self._lanes["validate"].alive,
            "store_alive": self._lanes["store"].alive,
        }

    # -- chaos seam ---------------------------------------------------

    def fail_next(self, stage: str) -> None:
        """Arm a one-shot worker death on ``stage``'s next job.

        The chaos injector's stage-boundary crash corpus uses this for
        the off-loop stages; it also fires at ``workers == 0`` so the
        respawn accounting is exercised identically in the sim.
        """
        self._lanes[stage].fail_next = True

    # -- stage entry points -------------------------------------------

    async def run_validate(self, fn, *args, nbytes: int = 0):
        return await self._run(self._lanes["validate"], fn, args, nbytes, False)

    async def run_store(self, fn, *args, nbytes: int = 0, offload: bool = False):
        """``offload=True``: keep the job off-loop even at ``workers == 0``
        (via the loop's default executor — what ``asyncio.to_thread``
        did).  For call sites that were ALREADY threaded before staging
        (the mempool checkpoint) and must not regress onto the loop when
        staging is off; under the simulator both paths are synchronous,
        so the determinism contract is unaffected."""
        return await self._run(self._lanes["store"], fn, args, nbytes, offload)

    async def _run(self, lane: _Lane, fn, args, nbytes: int, offload: bool):
        lane.depth += 1
        lane.queued_bytes += nbytes
        lane.jobs += 1
        try:
            try:
                return await self._submit(lane, fn, args, offload)
            except WorkerCrash:
                self._respawn(lane)
                # Retry once: a worker death must not lose the job (the
                # store lane's job IS the durability chain).  A second
                # crash propagates to the caller's error path.
                return await self._submit(lane, fn, args, offload)
        finally:
            lane.depth -= 1
            lane.queued_bytes -= nbytes

    async def _submit(self, lane: _Lane, fn, args, offload: bool = False):
        if lane.pool is None:
            if offload:
                loop = asyncio.get_running_loop()
                return await loop.run_in_executor(
                    None, self._call, lane, fn, args
                )
            # Staging off: inline, no awaits — scheduling-identical to
            # the historical single-threaded node.
            return self._call(lane, fn, args)
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                lane.pool, self._call, lane, fn, args
            )
        except concurrent.futures.BrokenExecutor as e:
            raise WorkerCrash(f"{lane.name} worker pool broken") from e
        except RuntimeError as e:
            # submit() on a shut-down pool — the real-world shape of a
            # dead worker (TaskStop, interpreter teardown races).
            if "shutdown" in str(e) or "interpreter" in str(e):
                raise WorkerCrash(f"{lane.name} worker pool dead") from e
            raise

    def _call(self, lane: _Lane, fn, args):
        if lane.fail_next:
            lane.fail_next = False
            lane.alive = False
            raise WorkerCrash(f"injected {lane.name} worker death")
        return fn(*args)

    def _respawn(self, lane: _Lane) -> None:
        with self._respawn_lock:
            lane.respawn()
        if self.on_respawn is not None:
            self.on_respawn(lane.name)

    # -- lifecycle ----------------------------------------------------

    def drain_and_close(self) -> None:
        """Flush queued lane jobs and release the worker threads.

        ``shutdown(wait=True)`` runs everything already submitted — the
        store lane's queue drains in append order before the node closes
        the store, so stop() never races its own writer.
        """
        for lane in self._lanes.values():
            if lane.pool is not None:
                lane.pool.shutdown(wait=True)
                lane.pool = None
            lane.alive = False
