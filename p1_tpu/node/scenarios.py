"""The scenario corpus: consensus emergent behavior at simulated scale.

The north star asks for "as many scenarios as you can imagine"; this
module is the library that opens — each scenario a deterministic
discrete-event run (node/netsim.py) of REAL ``Node`` instances
(consensus, mempool, governor, supervision, address book — nothing
mocked) that asserts a convergence or containment metric in bounded
*virtual* time.  The Bitcoin-Core lineage names the families:

- **partition-heal** — the mesh splits (600/400 at the flagship scale),
  both sides keep mining, the cut heals, and every node must converge
  to the one heaviest tip with the ledger-sum invariant intact.  This
  scenario found a real propagation gap on its first 1000-node run:
  batch-synced blocks were never re-announced, so regions with no
  direct link across the old cut never converged (node.py
  ``_announce_tip``).
- **flash-crowd** — hundreds of fresh nodes join at once against one
  seed (the thundering-herd IBD); everyone must reach the seed's tip
  even though the seed's MAX_PEERS/MAX_HANDSHAKING caps refuse most of
  the crowd, which must sync through each other instead.
- **churn** — waves of nodes stop and restart (same identity, same
  address) while mining continues; the survivors and the returners must
  converge and conserve.
- **eclipse** — attackers flood a victim's address book from many
  hosts and camp its inbound slots; the tried/new bucket split and the
  per-host ADDR budgets must keep the victim attached to the honest
  mesh and its book bounded.
- **wan** — regions with asymmetric inter-region latency/bandwidth;
  convergence must hold and measured propagation delay must reflect
  the configured geography (the sanity proof that the latency model is
  real, and the rig for propagation studies).

Every report carries ``trace_digest`` — two runs with the same seed
are byte-identical (tests/test_netsim.py asserts it), so any scenario
failure is replayable by seed alone.  `p1 sim` runs these from the
command line and prints the report as one JSON line.
"""

from __future__ import annotations

import asyncio
import random
import time

from p1_tpu.node.netsim import NODE_PORT, LinkProfile, SimNet

__all__ = ["SCENARIOS", "run_scenario"]


def _topology_peers(rng: random.Random, i: int, degree: int) -> list[int]:
    """Backbone + random small-world out-edges for node ``i``: always
    dial ``i-1`` (so any CONTIGUOUS index split leaves both sides
    internally connected — the partition scenario's well-posedness),
    plus ``degree-1`` random earlier nodes for short gossip paths."""
    if i == 0:
        return []
    extra = rng.sample(range(i - 1), min(i - 1, degree - 1))
    return [i - 1, *extra]


def _report(
    net: SimNet, scenario: str, t0: float, repro_flags: str = "", **extra
) -> dict:
    from p1_tpu.node.telemetry import propagation_summary_ms

    report = {
        "scenario": scenario,
        "seed": net.seed,
        # One-flag deterministic repro: every report names the exact
        # command whose re-run must reproduce ``trace_digest`` byte for
        # byte (tests/test_cli.py asserts exactly that).
        "repro": f"p1 sim {scenario} --seed {net.seed}"
        + (f" {repro_flags}" if repro_flags else ""),
        "nodes": len(net.nodes),
        "virtual_s": round(net.clock.now, 3),
        "wall_s": round(time.monotonic() - t0, 3),
        "events": net.net.events,
        "converged": net.converged(),
        "ledger_conserved": net.ledger_conserved(),
        "heights": {
            "min": min(net.heights()),
            "max": max(net.heights()),
        },
        "reorgs_total": sum(
            n.metrics.reorgs for n in net.nodes.values()
        ),
        # Telemetry timeline (round 14): the nodes' propagation
        # histograms merged, in VIRTUAL milliseconds — what lets a
        # scenario assert a p95 propagation bound instead of bare
        # convergence.  None when telemetry is disabled.
        "telemetry": {
            "propagation": propagation_summary_ms(
                n.telemetry for n in net.nodes.values()
            )
        },
        **extra,
    }
    report["trace_digest"] = net.trace_digest()
    return report


# -- partition-heal ------------------------------------------------------


def partition_heal(
    nodes: int = 1000,
    seed: int = 0,
    split: float = 0.6,
    blocks_major: int = 4,
    blocks_minor: int = 2,
    degree: int = 4,
    difficulty: int = 8,
    heal_timeout_vs: float = 180.0,
    wall_limit_s: float | None = 420.0,
    telemetry: bool = True,
    pipeline_workers: int = 0,
) -> dict:
    """The flagship: mesh splits ``split``/1-``split``, both sides mine,
    the cut heals, one tip wins everywhere.  ok = global convergence at
    the majority chain's height, mass reorgs on the minority side, and
    exact ledger conservation, all inside ``heal_timeout_vs`` virtual
    seconds of the heal.  ``telemetry=False`` disables the nodes'
    latency recording — the trace digest must not move (the round-14
    observer contract; tests/test_telemetry.py runs this scenario both
    ways and compares).  ``pipeline_workers`` stages every node's
    validate/store pipeline (node/pipeline.py) — the same digest
    contract holds: lane jobs are synchronous under the virtual loop,
    so staging on/off must not move the trace (tests/test_pipeline.py
    runs this scenario both ways at 200 nodes and compares)."""
    net = SimNet(
        seed=seed,
        difficulty=difficulty,
        telemetry=telemetry,
        pipeline_workers=pipeline_workers,
    )
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0x70B0)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, degree)]
            )
        hosts = list(net.nodes)
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        miner_a = net.nodes[hosts[0]]
        for _ in range(2):
            await net.mine_on(miner_a, spacing_s=2.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "pre-partition mesh never converged"

        na = int(nodes * split)
        side_a, side_b = hosts[:na], hosts[na:]
        net.net.partition(side_a, side_b)
        miner_b = net.nodes[side_b[0]]
        for _ in range(blocks_major):
            await net.mine_on(miner_a, spacing_s=2.0)
        for _ in range(blocks_minor):
            await net.mine_on(miner_b, spacing_s=2.0)
        sides_ok = await net.run_until(
            lambda: net.converged(side_a) and net.converged(side_b),
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        diverged = len(net.tips()) == 2

        heal_at = net.clock.now
        net.net.heal()
        # One fresh block on the majority side: the announcement that
        # races the heal (nodes with cross links hear it immediately;
        # everyone else must hear it through the post-sync tip
        # announce).
        await net.mine_on(miner_a, spacing_s=2.0)
        final_height = 2 + blocks_major + 1
        healed = await net.run_until(
            lambda: net.converged() and min(net.heights()) == final_height,
            heal_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        heal_vs = net.clock.now - heal_at
        minority_reorged = sum(
            1 for h in side_b if net.nodes[h].metrics.reorgs > 0
        )
        report = _report(
            net, "partition-heal", t0,
            split=[len(side_a), len(side_b)],
            sides_converged_under_partition=sides_ok,
            tips_diverged=diverged,
            healed=healed,
            heal_virtual_s=round(heal_vs, 3),
            final_height=final_height,
            minority_nodes_reorged=minority_reorged,
        )
        report["ok"] = bool(
            healed
            and diverged
            and sides_ok
            and report["converged"]
            and report["ledger_conserved"]
            # The minority side really did live on its own chain and
            # really was reorged back — blocks_minor > 0 makes this a
            # structural requirement, not a vacuous pass.
            and (blocks_minor == 0 or minority_reorged >= 0.9 * len(side_b))
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- flash-crowd IBD -----------------------------------------------------


def flash_crowd(
    joiners: int = 500,
    chain_height: int = 20,
    seed: int = 0,
    difficulty: int = 8,
    join_window_vs: float = 5.0,
    ibd_timeout_vs: float = 300.0,
    wall_limit_s: float | None = 420.0,
) -> dict:
    """``joiners`` fresh nodes storm one seed node inside
    ``join_window_vs`` virtual seconds.  The seed's MAX_PEERS /
    MAX_HANDSHAKING caps refuse most of the herd — each joiner also
    knows one random earlier joiner, and the crowd must sync through
    itself.  ok = every node at the seed's tip within the budget."""
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0xF1A5)
        seed_node = await net.add_node()
        seed_host = net.host_name(0)
        for _ in range(chain_height):
            await net.mine_on(seed_node, spacing_s=0.05)
        assert seed_node.chain.height == chain_height

        stagger = join_window_vs / max(1, joiners)
        for i in range(1, joiners + 1):
            peers = [seed_host]
            if i > 1:
                peers.append(net.host_name(rng.randrange(1, i)))
            await net.add_node(peers=peers)
            await asyncio.sleep(stagger)
        join_done = net.clock.now

        done = await net.run_until(
            lambda: min(net.heights()) == chain_height and net.converged(),
            ibd_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        ibd_vs = net.clock.now - join_done
        seed_peers = seed_node.peer_count()
        report = _report(
            net, "flash-crowd", t0,
            joiners=joiners,
            chain_height=chain_height,
            ibd_complete=done,
            ibd_virtual_s=round(ibd_vs, 3),
            seed_peer_count=seed_peers,
            # The crowd was bigger than the seed's open-arms policy:
            # the interesting regime is the refused majority syncing
            # through the mesh, and this records that it happened.
            seed_capped=seed_peers < joiners,
        )
        report["ok"] = bool(
            done and report["converged"] and report["ledger_conserved"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- churn storm ---------------------------------------------------------


def churn_storm(
    nodes: int = 60,
    cycles: int = 5,
    churn_frac: float = 0.25,
    seed: int = 0,
    degree: int = 4,
    difficulty: int = 8,
    settle_timeout_vs: float = 120.0,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Waves of nodes vanish mid-gossip and return (same identity, same
    address — a restart, not a new peer) while the survivors keep
    mining.  ok = after the storm, every node — returners included —
    converges on one tip and conserves the ledger."""
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0xC4B1)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, degree)]
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            60, step=0.1, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-churn"

        restarts = 0
        for _cycle in range(cycles):
            victims = rng.sample(hosts[1:], int((nodes - 1) * churn_frac))
            for h in victims:
                await net.stop_node(h)
            # Mine while they are gone: the returners restart behind
            # the tip and must catch up through ordinary sync.
            await net.mine_on(miner, spacing_s=1.0)
            await asyncio.sleep(2.0)
            for h in victims:
                await net.restart_node(h)
                restarts += 1
            await net.mine_on(miner, spacing_s=1.0)
            await asyncio.sleep(2.0)

        final_height = 2 + 2 * cycles
        settled = await net.run_until(
            lambda: net.converged() and min(net.heights()) == final_height,
            settle_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        report = _report(
            net, "churn", t0,
            cycles=cycles,
            restarts=restarts,
            settled=settled,
            final_height=final_height,
        )
        report["ok"] = bool(
            settled and report["converged"] and report["ledger_conserved"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- eclipse attempt -----------------------------------------------------


def eclipse(
    honest: int = 24,
    attackers: int = 8,
    spam_rounds: int = 30,
    seed: int = 0,
    difficulty: int = 8,
    wall_limit_s: float | None = 240.0,
) -> dict:
    """Attackers flood a victim's address book from ``attackers``
    distinct hosts — hundreds of addresses pointing into attacker
    space — and run hostile listeners the victim's discovery may dial.
    The round-4 eclipse defenses under test: gossip can only churn the
    "new" bucket (handshake-verified "tried" entries are out of reach),
    per-HOST token buckets clamp unsolicited ADDR no matter how many
    frames arrive, and the book stays bounded.  ok = the victim keeps
    ≥1 honest connection, keeps converging with the honest mesh, and
    attacker addresses never exceed the budgeted trickle."""
    from p1_tpu.node import protocol
    from p1_tpu.node.node import MAX_KNOWN_ADDRS, MAX_TRIED_ADDRS
    from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    ATTACKER_NET = "66.6."

    async def main():
        rng = random.Random(seed ^ 0xEC11)
        for i in range(honest):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)]
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        # The victim: discovery ON — exactly the machinery an eclipse
        # targets (it dials what the book tells it to).
        victim_host = "10.9.9.9"
        victim = await net.add_node(
            name=victim_host, peers=[hosts[0]], target_peers=4
        )
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and victim.chain.height == 2,
            60, step=0.1, wall_limit_s=wall_limit_s,
        ), "victim never joined the honest mesh"

        # Hostile listeners the poisoned book would dial into: they
        # answer the handshake (advertising height 0 — nothing to
        # serve) and otherwise waste the victim's time.
        listeners = []
        chain = make_blocks(1, difficulty)  # genesis only: right chain id
        for a in range(attackers):
            hp = HostilePeer(
                chain,
                plan=FaultPlan(hello_height=0),
                transport=net.net.host(f"{ATTACKER_NET}0.{a}"),
                host=f"{ATTACKER_NET}0.{a}",
                rng=random.Random(seed * 1000 + a),
            )
            await hp.start()
            listeners.append(hp)

        async def spam(a: int) -> None:
            """One attacker host streams ADDR frames at the victim:
            64 addresses per frame, every frame pointing into attacker
            space (the listeners above plus void)."""
            srng = random.Random(seed * 77 + a)
            src = f"{ATTACKER_NET}0.{a}"
            try:
                reader, writer = await net.net.host(src).connect(
                    victim_host, NODE_PORT
                )
                await protocol.write_frame(
                    writer,
                    protocol.encode_hello(
                        protocol.Hello(
                            miner.chain.genesis.block_hash(),
                            0,
                            listeners[a].port,
                            srng.getrandbits(64) | 1,
                        )
                    ),
                )
                await protocol.read_frame(reader)  # victim's HELLO
                for _ in range(spam_rounds):
                    addrs = [
                        (
                            f"{ATTACKER_NET}{srng.randrange(1, 250)}."
                            f"{srng.randrange(250)}",
                            srng.randrange(1, 0xFFFF),
                        )
                        for _ in range(64)
                    ]
                    await protocol.write_frame(
                        writer, protocol.encode_addr(addrs)
                    )
                    await asyncio.sleep(0.2)
                writer.close()
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                pass  # victim dropped us: also an answer

        await asyncio.gather(*(spam(a) for a in range(attackers)))
        await asyncio.sleep(5.0)

        # Post-storm: the honest mesh keeps mining; the victim must
        # still follow it.
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        followed = await net.run_until(
            lambda: victim.chain.tip_hash == miner.chain.tip_hash,
            60, step=0.1, wall_limit_s=wall_limit_s,
        )

        honest_set = set(hosts)
        honest_links = sum(
            1
            for p in victim._peers.values()
            if p.host in honest_set
        )
        tried_attacker = sum(
            1
            for (h, _pt) in victim._tried_addrs
            if h.startswith(ATTACKER_NET)
        )
        known_attacker = sum(
            1
            for (h, _pt) in victim._known_addrs
            if h.startswith(ATTACKER_NET)
        )
        book = len(victim._known_addrs) + len(victim._tried_addrs)
        spam_sent = attackers * spam_rounds * 64
        report = _report(
            net, "eclipse", t0,
            attackers=attackers,
            spam_addrs_sent=spam_sent,
            victim_honest_links=honest_links,
            victim_followed_honest_tip=followed,
            tried_bucket_attacker_entries=tried_attacker,
            new_bucket_attacker_entries=known_attacker,
            address_book_size=book,
            address_book_bounded=book
            <= MAX_KNOWN_ADDRS + MAX_TRIED_ADDRS,
        )
        # The ADDR budget admits ~1 address/host/second plus the burst:
        # anything near the spam volume means the bucket failed.
        budget_held = known_attacker <= attackers * 80
        report["ok"] = bool(
            followed
            and honest_links >= 1
            and tried_attacker == 0
            and budget_held
            and report["address_book_bounded"]
            and report["ledger_conserved"]
        )
        for hp in listeners:
            await hp.stop()
        await net.stop_all()
        return report

    return net.run(main())


# -- WAN topology --------------------------------------------------------

#: One-way inter-region latencies (seconds) for the wan scenario —
#: deliberately asymmetric (routing asymmetry is real) so the model is
#: exercised per DIRECTION.
_WAN_LATENCY = {
    ("us", "eu"): 0.040,
    ("eu", "us"): 0.048,
    ("us", "asia"): 0.080,
    ("asia", "us"): 0.092,
    ("eu", "asia"): 0.120,
    ("asia", "eu"): 0.132,
    ("us", "au"): 0.095,
    ("au", "us"): 0.110,
    ("eu", "au"): 0.140,
    ("au", "eu"): 0.155,
    ("asia", "au"): 0.060,
    ("au", "asia"): 0.070,
}

#: The wan scenario's default propagation SLO (virtual ms): a few
#: gossip hops across the worst configured path.  Applied when the
#: caller passes no explicit bound AND telemetry makes it measurable.
WAN_DEFAULT_P95_BOUND_MS = 1500.0


def wan(
    region_nodes: int = 10,
    blocks: int = 6,
    seed: int = 0,
    difficulty: int = 8,
    inter_bandwidth_bps: float = 100e6,
    wall_limit_s: float | None = 240.0,
    telemetry: bool = True,
    propagation_p95_bound_ms: float | None = None,
) -> dict:
    """Four regions (us/eu/asia/au) with asymmetric inter-region
    latency and shaped bandwidth; blocks are mined round-robin across
    regions.  ok = global convergence, the measured propagation p95
    actually shows the geography (at least one inter-region one-way
    latency — the proof the latency model is load-bearing), AND — from
    the round-14 telemetry histograms — the mesh-wide virtual-time
    propagation p95 stays under ``propagation_p95_bound_ms``: a few
    gossip hops across the worst configured path, an actual latency SLO
    instead of bare convergence.

    ``propagation_p95_bound_ms``: None applies the default SLO
    (``WAN_DEFAULT_P95_BOUND_MS``) when the histograms exist and marks
    the SLO ``"unevaluated"`` — excluded from ``ok``, never silently
    passed — when telemetry is off; an EXPLICIT bound with telemetry
    disabled raises ``ValueError`` up front (a bound that cannot be
    measured must fail loudly, not fall back vacuously — the round-17
    fix; tests/test_scenarios.py carries the negative control)."""
    if not telemetry and propagation_p95_bound_ms is not None:
        raise ValueError(
            "a propagation p95 bound was requested but telemetry is "
            "disabled: the SLO is unmeasurable, not vacuously true"
        )
    regions = ("us", "eu", "asia", "au")
    net = SimNet(
        seed=seed,
        difficulty=difficulty,
        default_profile=LinkProfile(latency_s=0.002, jitter_s=0.001),
        telemetry=telemetry,
    )
    t0 = time.monotonic()

    def region_host(r: str, i: int) -> str:
        return f"10.{regions.index(r) + 1}.0.{i}"

    async def main():
        rng = random.Random(seed ^ 0x3A11)
        by_region: dict[str, list[str]] = {r: [] for r in regions}
        # Profiles first (between region /24s), then nodes: every pair
        # of cross-region hosts gets the matrix latency + shared
        # bandwidth shaping; intra-region stays on the LAN default.
        all_hosts = [
            (r, region_host(r, i))
            for r in regions
            for i in range(region_nodes)
        ]
        for ra, ha in all_hosts:
            for rb, hb in all_hosts:
                if ra != rb:
                    net.net.set_profile(
                        ha,
                        hb,
                        LinkProfile(
                            latency_s=_WAN_LATENCY[(ra, rb)],
                            jitter_s=0.004,
                            bandwidth_bps=inter_bandwidth_bps,
                        ),
                        symmetric=False,
                    )
        for idx, (r, host) in enumerate(all_hosts):
            peers = []
            mine_region = by_region[r]
            if mine_region:
                peers.append(mine_region[-1])  # region backbone
                if len(mine_region) > 1:
                    peers.append(mine_region[rng.randrange(len(mine_region))])
            if idx > 0 and (not mine_region or len(mine_region) % 3 == 1):
                # A gateway link into the regions dialed so far.
                others = [h for _r, h in all_hosts[:idx] if _r != r]
                if others:
                    peers.append(others[rng.randrange(len(others))])
            await net.add_node(name=host, peers=peers)
            by_region[r].append(host)
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "wan mesh never formed"

        for b in range(blocks):
            r = regions[b % len(regions)]
            await net.mine_on(
                net.nodes[by_region[r][0]], spacing_s=3.0
            )
        done = await net.run_until(
            lambda: net.converged() and min(net.heights()) == blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        summaries = [
            n.metrics.propagation_summary() for n in net.nodes.values()
        ]
        p95s = [s["p95_ms"] for s in summaries if s["p95_ms"] is not None]
        max_p95_ms = max(p95s) if p95s else 0.0
        min_inter_ms = 1e3 * min(_WAN_LATENCY.values())
        report = _report(
            net, "wan", t0,
            regions={r: len(by_region[r]) for r in regions},
            blocks=blocks,
            propagation_max_p95_ms=max_p95_ms,
            min_inter_region_latency_ms=min_inter_ms,
            geography_visible=max_p95_ms >= min_inter_ms,
        )
        # The telemetry-histogram SLO: mesh-wide p95 propagation (in
        # virtual ms, merged across every node) under the bound.  Three
        # explicit states, none vacuous (the round-17 fix — the old
        # code read "no histogram" as "bounded"):
        #   evaluated    — histograms exist, the bound was checked;
        #   unevaluated  — telemetry off AND no bound requested: the
        #                  SLO is out of scope, marked so, and excluded
        #                  from ``ok`` (never counted as a pass);
        #   unmeasurable — a bound applies but the histograms are
        #                  missing (telemetry on, nothing recorded):
        #                  that is a FAILURE, not a pass.
        prop = report["telemetry"]["propagation"]
        bound = (
            WAN_DEFAULT_P95_BOUND_MS
            if propagation_p95_bound_ms is None
            else propagation_p95_bound_ms
        )
        report["propagation_p95_bound_ms"] = bound if telemetry else None
        if not telemetry:
            report["propagation_slo"] = "unevaluated"
            report["propagation_bounded"] = None
            slo_ok = True  # out of scope by request, and SAYS so
        elif prop is None:
            report["propagation_slo"] = "unmeasurable"
            report["propagation_bounded"] = False
            slo_ok = False
        else:
            report["propagation_slo"] = "evaluated"
            report["propagation_bounded"] = prop["p95_ms"] <= bound
            slo_ok = report["propagation_bounded"]
        report["ok"] = bool(
            done
            and report["converged"]
            and report["ledger_conserved"]
            and report["geography_visible"]
            and slo_ok
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- snapshot join (untrusted snapshot sync) -----------------------------


def snapshot_join(
    nodes: int = 16,
    chain_blocks: int = 10,
    seed: int = 0,
    difficulty: int = 8,
    interval: int = 4,
    lie: str | None = None,
    liar_height: int = 12,
    verdict_timeout_vs: float = 300.0,
    wall_limit_s: float | None = 240.0,
) -> dict:
    """Untrusted snapshot sync (chain/snapshot.py) at mesh scale.

    Honest form (``lie=None``): a fresh node joins a converged mesh
    with ``--snapshot-sync`` on, boots ASSUMED from a peer-served
    checkpoint snapshot, serves balance queries immediately, and must
    flip to fully-validated once the background replay reproduces the
    state root.  The report measures the assumed-boot and flip times in
    virtual seconds, and re-checks every balance the joiner reported
    while ASSUMED against the audit view of the validated chain — the
    never-contradicted invariant.

    Lying form (``lie`` in "balance"/"root"/"truncate"/"stall"): the
    joiner's FIRST peer is a hostile snapshot server running that
    pathology on a taller fork.  ok = the joiner detects/contains it
    (divergence + quarantine for the internally-consistent "balance"
    lie; refusal/failover for the rest), ends fully-validated, and the
    whole network still converges with the ledger conserved."""
    from p1_tpu.chain.ledger import balances as audit_balances
    from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    WALLET = "snapshot-wallet"

    async def main():
        rng = random.Random(seed ^ 0x54A9)
        for i in range(nodes):
            await net.add_node(
                peers=[
                    net.host_name(j) for j in _topology_peers(rng, i, 3)
                ],
                snapshot_interval=interval,
                **({"miner_id": WALLET} if i == 0 else {}),
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(chain_blocks):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == chain_blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-join"

        peers = [hosts[0], hosts[1]]
        liar = None
        if lie is not None:
            from p1_tpu.node.protocol import MsgType

            if lie in ("balance", "root"):
                plan = FaultPlan(snapshot_lie=lie)
            elif lie == "truncate":
                plan = FaultPlan(snapshot_chunks=1)
            else:
                plan = FaultPlan(swallow=frozenset({MsgType.GETSNAPSHOT}))
            src = "66.9.9.1"
            liar = HostilePeer(
                make_blocks(liar_height, difficulty, miner_id="snapliar"),
                plan=plan,
                transport=net.net.host(src),
                host=src,
                rng=random.Random(seed * 31 + 7),
            )
            await liar.start()
            peers = [f"{src}:{liar.port}", hosts[0]]

        join_at = net.clock.now
        joiner = await net.add_node(
            name="10.99.9.9",
            peers=peers,
            snapshot_sync=True,
            snapshot_interval=interval,
            snapshot_min_lead=2,
        )
        assumed = await net.run_until(
            lambda: joiner.validation_state == "assumed",
            60, step=0.1, wall_limit_s=wall_limit_s,
        )
        assumed_vs = net.clock.now - join_at
        samples: list[tuple[int, bytes, int]] = []

        def sample():
            if joiner.validation_state == "assumed":
                samples.append(
                    (
                        joiner.chain.height,
                        joiner.chain.tip_hash,
                        joiner.chain.balance(WALLET),
                    )
                )
            return False

        await net.run_until(
            sample, 2.0, step=0.5, wall_limit_s=wall_limit_s
        )
        verdict = await net.run_until(
            lambda: joiner.validation_state == "validated"
            and joiner._bg_chain is None,
            verdict_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        verdict_vs = net.clock.now - join_at
        # Post-verdict: one more honest block must reach the joiner.
        await net.mine_on(miner, spacing_s=1.0)
        settled = await net.run_until(
            lambda: net.converged(), 120, step=0.25,
            wall_limit_s=wall_limit_s,
        )
        contradicted = 0
        ref = net.nodes[hosts[0]].chain
        if joiner.metrics.snapshot_flips:
            for height, tip_hash, reported in samples:
                if ref.main_hash_at(height) != tip_hash:
                    continue  # claim's block reorged away: retracted
                blocks = [
                    ref._block_at(ref.main_hash_at(h))
                    for h in range(height + 1)
                ]
                if audit_balances(blocks).get(WALLET, 0) != reported:
                    contradicted += 1
        report = _report(
            net, "snapshot-join", t0,
            lie=lie,
            assumed=assumed,
            assumed_virtual_s=round(assumed_vs, 3),
            verdict=verdict,
            verdict_virtual_s=round(verdict_vs, 3),
            flips=joiner.metrics.snapshot_flips,
            divergences=joiner.metrics.snapshot_divergences,
            assumed_samples=len(samples),
            samples_contradicted=contradicted,
        )
        if lie is None:
            report["ok"] = bool(
                assumed
                and verdict
                and settled
                and joiner.metrics.snapshot_flips == 1
                and joiner.metrics.snapshot_divergences == 0
                and contradicted == 0
                and report["ledger_conserved"]
            )
        elif lie == "balance":
            # Internally consistent lie: adopted, then CAUGHT by the
            # background replay — quarantined, fallen back, converged.
            report["ok"] = bool(
                assumed
                and verdict
                and settled
                and joiner.metrics.snapshot_divergences >= 1
                and joiner.metrics.snapshot_flips == 0
                and report["ledger_conserved"]
            )
        else:
            # root/truncate/stall: refused or failed over BEFORE any
            # state was trusted — the joiner may end up assuming an
            # honest peer's snapshot instead (and must then flip).
            report["ok"] = bool(
                verdict
                and settled
                and contradicted == 0
                and report["ledger_conserved"]
            )
        if liar is not None:
            await liar.stop()
        await net.stop_all()
        return report

    return net.run(main())


# -- far field: the sharded 10k-node scenario ----------------------------


def far_field(
    nodes: int = 10_000,
    full_nodes: int = 16,
    blocks: int = 8,
    seed: int = 0,
    difficulty: int = 8,
    degree: int = 4,
    shards: int = 1,
    processes: bool | None = None,
    spacing_s: float = 4.0,
    far_settle_bound_ms: float = 60_000.0,
    wall_limit_s: float | None = 420.0,
) -> dict:
    """An order of magnitude past the full simulator: a ``full_nodes``
    core mesh of REAL nodes mines and converges as usual, and every
    announcement then propagates through a ``nodes - full_nodes``
    header-only far field (node/farfield.py) — sharded ``shards`` ways,
    across processes when ``shards > 1`` (``processes=False`` keeps the
    same sharded exchange in one process for determinism pairs).

    ok = the core converges with the ledger conserved, EVERY far-field
    node ends on the core's final tip, and the far field's last header
    arrival lands within ``far_settle_bound_ms`` virtual ms of its
    injection (the convergence-lag SLO; an impossible bound must fail —
    the control test).  The report's ``trace_digest`` is the MERGED
    digest — core event trace + far-field delivery trace — and must be
    byte-identical for the same seed at 1 shard and at N shards, in
    process and across processes (the round-17 acceptance pair)."""
    import hashlib

    from p1_tpu.node.farfield import run_far_field

    assert full_nodes >= 2 and nodes > full_nodes
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    feed: list[tuple[float, int, str, str]] = []

    async def main():
        rng = random.Random(seed ^ 0xFA2F)
        for i in range(full_nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)]
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "core mesh never formed"
        for _ in range(blocks):
            t_inject = net.clock.now
            block = await net.mine_on(miner, spacing_s=spacing_s)
            parent = feed[-1][2] if feed else ""
            feed.append(
                (
                    t_inject,
                    miner.chain.height,
                    block.block_hash().hex()[:16],
                    parent,
                )
            )
        done = await net.run_until(
            lambda: net.converged() and min(net.heights()) == blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        report = _report(
            net, "far-field", t0,
            repro_flags=f"--shards {shards}",
            core_done=done,
        )
        await net.stop_all()
        return report

    report = net.run(main())
    far = run_far_field(
        nodes - full_nodes,
        seed,
        feed,
        degree=degree,
        shards=shards,
        processes=processes,
        wall_limit_s=wall_limit_s,
    )
    core_digest = report["trace_digest"]
    report.update(
        nodes=nodes,
        full_nodes=full_nodes,
        far_nodes=far.nodes,
        shards=far.shards,
        shard_processes=far.processes,
        far_deliveries=far.deliveries,
        far_barrier_rounds=far.rounds,
        far_converged_nodes=far.converged_nodes,
        far_converged=far.converged,
        far_settle_ms=far.settle_ms,
        far_settle_bound_ms=far_settle_bound_ms,
        far_propagation_p50_ms=far.propagation_p50_ms,
        far_propagation_p95_ms=far.propagation_p95_ms,
        core_trace_digest=core_digest,
        far_trace_digest=far.trace_digest,
        # THE merged digest: the shard-count-invariance witness.
        trace_digest=hashlib.sha256(
            (core_digest + far.trace_digest).encode()
        ).hexdigest(),
        wall_s=round(time.monotonic() - t0, 3),
    )
    report["ok"] = bool(
        report["core_done"]
        and report["converged"]
        and report["ledger_conserved"]
        and far.converged
        and far.settle_ms <= far_settle_bound_ms
    )
    return report


# -- selfish mining / block withholding ----------------------------------


def selfish_mining(
    honest: int = 20,
    alpha: float = 0.3,
    finds: int = 120,
    seed: int = 0,
    difficulty: int = 8,
    find_spacing_s: float = 2.0,
    amplification_bound: float = 1.10,
    margin: float = 0.05,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Eyal–Sirer selfish mining against the real mesh: an attacker
    with hashrate fraction ``alpha`` mines PRIVATELY (an isolated full
    node — nobody dials it, it dials nobody) and releases strategically
    through an honest entry node: withhold while ahead, reveal the
    matching prefix when far ahead, release everything when the honest
    chain draws within one (the override), race at a tie.

    The containment bound under test: this mesh gives the attacker
    γ ≈ 0 — honest nodes NEVER mine on the attacker's block in a tie,
    because fork choice keeps the first-seen tip at equal weight and
    the mesh heard its own block first — and below the γ=0 profit
    threshold (α < ~1/3) selfish mining must then UNDER-perform honest
    mining.  ok asserts the attacker's realized share of the final
    chain's coinbases ≤ ``alpha * amplification_bound + margin`` (plus
    the structural bits: the attack really ran — blocks were withheld,
    at least one override reorged the mesh — and the mesh still
    converged with the ledger conserved).  ``margin=-1`` is the
    impossible-bound control."""
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    ATTACKER = "selfish"

    async def main():
        rng = random.Random(seed ^ 0x5E1F)
        for i in range(honest):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                miner_id=f"honest-{i}",
            )
        hosts = list(net.nodes)
        rep = net.nodes[hosts[0]]  # honest representative / miner
        entry = net.nodes[hosts[1]]  # where attacker blocks enter
        attacker = await net.add_node(
            name="10.66.6.6", peers=[], miner_id=ATTACKER
        )
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        # Warmup: two public blocks (the attacker sees them too — it
        # tracks the public chain even while mining its own).
        for _ in range(2):
            b = await net.mine_on(rep, spacing_s=1.0)
            await attacker._handle_block(b)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            60, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-attack"
        warmup_height = rep.chain.height

        withheld: list = []  # unpublished suffix of the private branch
        published: set[bytes] = {rep.chain.tip_hash}
        stats = {
            "withheld_blocks": 0,
            "reveals": 0,
            "overrides": 0,
            "races": 0,
            "attacker_finds": 0,
            "honest_finds": 0,
        }

        async def publish(upto_height: int | None = None) -> None:
            """Release withheld blocks (all, or the prefix at or below
            ``upto_height``) into the mesh through the entry node."""
            while withheld and (
                upto_height is None or withheld[0][0] <= upto_height
            ):
                _h, blk = withheld.pop(0)
                published.add(blk.block_hash())
                await entry._handle_block(blk)
            stats["reveals"] += 1

        for _find in range(finds):
            if rng.random() < alpha:
                stats["attacker_finds"] += 1
                parent_hash = attacker.chain.tip_hash
                blk = await net.mine_on(attacker)  # no peers: stays private
                withheld.append((attacker.chain.height, blk))
                stats["withheld_blocks"] += 1
                if (
                    parent_hash in published
                    and rep.chain.tip_hash != parent_hash
                ):
                    # We were racing at a tie and just pulled ahead:
                    # release immediately — the override that wins both.
                    stats["overrides"] += 1
                    await publish()
            else:
                stats["honest_finds"] += 1
                blk = await net.mine_on(rep)
                await attacker._handle_block(blk)
                if not withheld:
                    pass  # nothing private: honest block just extends
                elif attacker.chain.tip_hash == rep.chain.tip_hash:
                    # The public chain outweighed us: adopt — whatever
                    # was still withheld died on the abandoned branch.
                    withheld.clear()
                else:
                    lead = attacker.chain.height - rep.chain.height
                    if lead <= 0:
                        stats["races"] += 1
                        await publish()  # tie: race the honest block
                    elif lead == 1:
                        stats["overrides"] += 1
                        await publish()  # one ahead: override outright
                    else:
                        await publish(upto_height=rep.chain.height)
            await asyncio.sleep(find_spacing_s)

        # Finale: release anything still private, settle, and let one
        # fresh honest block break any residual tie mesh-wide.
        if withheld:
            await publish()
        await asyncio.sleep(5.0)
        b = await net.mine_on(rep, spacing_s=2.0)
        await attacker._handle_block(b)
        settled = await net.run_until(
            net.converged, 120, step=0.25, wall_limit_s=wall_limit_s
        )

        chain = rep.chain
        revenue = {"attacker": 0, "honest": 0}
        for h in range(warmup_height + 1, chain.height + 1):
            block = chain._block_at(chain.main_hash_at(h))
            who = block.txs[0].recipient
            revenue["attacker" if who == ATTACKER else "honest"] += 1
        total = revenue["attacker"] + revenue["honest"]
        share = revenue["attacker"] / max(1, total)
        actual_alpha = stats["attacker_finds"] / max(1, finds)
        # Bound against the REALIZED hashrate fraction (the seeded
        # draw), not the nominal alpha: the claim is about strategy
        # amplification, not sampling noise.
        bound = actual_alpha * amplification_bound + margin
        mesh_reorgs = sum(
            net.nodes[h].metrics.reorgs for h in hosts
        )
        report = _report(
            net, "selfish-mining", t0,
            alpha=alpha,
            actual_alpha=round(actual_alpha, 4),
            finds=finds,
            **stats,
            attacker_blocks_on_chain=revenue["attacker"],
            honest_blocks_on_chain=revenue["honest"],
            attacker_revenue_share=round(share, 4),
            honest_revenue_share=round(1 - share, 4),
            revenue_share_bound=round(bound, 4),
            containment_held=share <= bound,
            honest_mesh_reorgs=mesh_reorgs,
            settled=settled,
        )
        report["ok"] = bool(
            settled
            and report["converged"]
            and report["ledger_conserved"]
            and report["containment_held"]
            # The attack must actually have run, or the containment
            # claim is vacuous: private blocks were withheld, and at
            # least one override forced honest nodes through a reorg.
            and stats["withheld_blocks"] > 0
            and stats["overrides"] >= 1
            and mesh_reorgs >= 1
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- fee-spam economics vs the governor ----------------------------------


def fee_spam(
    nodes: int = 10,
    spammers: int = 3,
    honest_txs: int = 18,
    seed: int = 0,
    difficulty: int = 8,
    spam_fee: int = 0,
    honest_fee: int = 2,
    spam_rate_per_s: float = 120.0,
    storm_vs: float = 45.0,
    block_every_vs: float = 5.0,
    max_block_txs: int = 8,
    confirm_bound_blocks: int = 4,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Fee-market spam against the PR-4 governor: ``spammers`` hosts
    each fund ONE wallet with a single coinbase (the spend limit — spam
    must be protocol-valid, and validity costs balance), then stream TX
    frames at ``spam_rate_per_s`` — real signed zero/low-fee transfers,
    replayed and over-extended past the balance — at honest nodes,
    while an honest wallet submits ``honest_txs`` normal-fee transfers
    and miners keep producing small blocks (``max_block_txs`` squeezes
    capacity so ordering matters).

    The layered defense under test, measured separately: the
    governor's per-peer tx budget drops the firehose at the dispatch
    door (and escalates to a ban), pool admission's balance/debit
    accounting caps what one funded wallet can ever occupy, and
    fee-ordered block selection seats honest transactions first.

    ok = the never-starved invariant — EVERY honest transaction
    confirms within ``confirm_bound_blocks`` blocks of submission —
    plus: the spam genuinely pressured the door (admission drops > 0),
    the spend limit held (mined spam ≤ what the spam balance affords),
    and the mesh converged with the ledger conserved.
    ``confirm_bound_blocks=0`` is the impossible-bound control."""
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import BLOCK_REWARD, Transaction
    from p1_tpu.node import protocol
    from p1_tpu.node.governor import CLASS_TXS

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    spam_wallets = [
        Keypair.from_seed_text(f"p1-spam-{seed}-{k}") for k in range(spammers)
    ]
    honest_wallet = Keypair.from_seed_text(f"p1-honest-{seed}")
    payee = Keypair.from_seed_text(f"p1-payee-{seed}")

    async def main():
        rng = random.Random(seed ^ 0xFEE5)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                max_block_txs=max_block_txs,
                miner_id="pool",
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        # Funding: one coinbase per spam wallet (THE spend limit), two
        # for the honest wallet — by mining blocks whose coinbase pays
        # each wallet directly.
        for w in (*spam_wallets, honest_wallet, honest_wallet):
            miner.miner_id = w.account
            await net.mine_on(miner, spacing_s=1.0)
        miner.miner_id = "pool"
        fund_height = miner.chain.height
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == fund_height,
            60, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged post-funding"

        genesis = genesis_hash(difficulty)
        spam_budget = spammers * BLOCK_REWARD

        async def spam(k: int) -> dict:
            """One spammer host: HELLO, then a TX firehose — its funded
            set first, then replays and beyond-balance extensions."""
            srng = random.Random(seed * 91 + k)
            wallet = spam_wallets[k]
            victim = hosts[(k + 1) % len(hosts)]
            src = f"66.7.0.{k}"
            # Twice the affordable set: the second half is guaranteed
            # over-balance (amount 1 + fee over a BLOCK_REWARD budget).
            txs = [
                Transaction.transfer(
                    wallet, payee.account, 1, spam_fee, s, chain=genesis
                )
                for s in range(2 * BLOCK_REWARD)
            ]
            frames = [protocol.encode_tx(tx) for tx in txs]
            sent = dropped = 0
            deadline = net.clock.now + storm_vs
            try:
                reader, writer = await net.net.host(src).connect(
                    victim, NODE_PORT
                )
                await protocol.write_frame(
                    writer,
                    protocol.encode_hello(
                        protocol.Hello(
                            genesis, 0, 1, srng.getrandbits(64) | 1
                        )
                    ),
                )
                await protocol.read_frame(reader)
                i = 0
                while net.clock.now < deadline:
                    if writer.is_closing():
                        dropped = 1  # the ban layer severed the session
                        break
                    await protocol.write_frame(writer, frames[i % len(frames)])
                    sent += 1
                    i += 1
                    await asyncio.sleep(1.0 / spam_rate_per_s)
                writer.close()
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                dropped = 1  # governor escalation severed / refused us
            return {"sent": sent, "severed": dropped}

        async def honest_traffic() -> list[dict]:
            """The honest wallet: normal-fee transfers via its node's
            submit API, spread over the storm."""
            rows = []
            gap = storm_vs / (honest_txs + 1)
            for _ in range(honest_txs):
                await asyncio.sleep(gap)
                node = net.nodes[hosts[2]]
                acct = honest_wallet.account
                seqno = node.mempool.pending_next_seq(
                    acct, node.chain.nonce(acct)
                )
                tx = Transaction.transfer(
                    honest_wallet, payee.account, 1, honest_fee, seqno,
                    chain=genesis,
                )
                await node.submit_tx(tx)
                rows.append(
                    {
                        "txid": tx.txid(),
                        "submitted_vs": net.clock.now,
                        "submitted_height": miner.chain.height,
                    }
                )
            return rows

        async def mining() -> int:
            blocks = 0
            while net.clock.now < t_storm0 + storm_vs + 2 * block_every_vs:
                await asyncio.sleep(block_every_vs)
                await net.mine_on(miner)
                blocks += 1
            return blocks

        t_storm0 = net.clock.now
        spam_results, honest_rows, blocks_mined = (
            await asyncio.gather(
                asyncio.gather(*(spam(k) for k in range(spammers))),
                honest_traffic(),
                mining(),
            )
        )
        # Post-storm: drain any honest stragglers with a few clean
        # blocks, then settle.
        for _ in range(confirm_bound_blocks or 1):
            await net.mine_on(miner, spacing_s=1.0)
        settled = await net.run_until(
            net.converged, 120, step=0.25, wall_limit_s=wall_limit_s
        )

        chain = miner.chain
        confirmed = []
        for row in honest_rows:
            bhash = chain._tx_index.get(row["txid"])
            if bhash is not None:
                confirmed.append(
                    chain.height_of(bhash) - row["submitted_height"]
                )
        spam_mined = 0
        spam_accounts = {w.account for w in spam_wallets}
        for h in range(fund_height + 1, chain.height + 1):
            for tx in chain._block_at(chain.main_hash_at(h)).txs[1:]:
                if tx.sender in spam_accounts:
                    spam_mined += 1
        door_drops = sum(
            net.nodes[h].governor.admission_drops[CLASS_TXS] for h in hosts
        )
        spam_sent = sum(r["sent"] for r in spam_results)
        # Escalation reached the misbehavior layer: spam hosts scored
        # (and, transiently, banned — the 30 s ban itself expires).
        spam_scored = sum(
            1
            for k in range(spammers)
            if any(
                f"66.7.0.{k}" in net.nodes[h]._violations for h in hosts
            )
        )
        report = _report(
            net, "fee-spam", t0,
            spammers=spammers,
            spam_frames_sent=spam_sent,
            spammers_scored=spam_scored,
            admission_tx_drops=door_drops,
            spam_txs_mined=spam_mined,
            spam_budget_txs=spam_budget,
            blocks_mined_in_storm=blocks_mined,
            honest_submitted=len(honest_rows),
            honest_confirmed=len(confirmed),
            honest_confirm_blocks_max=max(confirmed, default=0),
            confirm_bound_blocks=confirm_bound_blocks,
            settled=settled,
        )
        report["ok"] = bool(
            settled
            and report["converged"]
            and report["ledger_conserved"]
            # Never starved: every honest tx confirmed, within bound.
            and len(confirmed) == len(honest_rows)
            and (max(confirmed, default=0) <= confirm_bound_blocks)
            # The flood was real (the door dropped frames) and the
            # spend limit held (mined spam within the funded budget).
            and door_drops > 0
            and spam_mined <= spam_budget
            and spam_sent > spam_budget
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- difficulty-retarget oscillation under hashrate shocks ---------------


def retarget_shock(
    nodes: int = 8,
    seed: int = 0,
    difficulty: int = 8,
    window: int = 8,
    spacing: int = 8,
    warm_windows: int = 2,
    shock_factor: int = 8,
    shock_windows: int = 4,
    recovery_windows: int = 10,
    overshoot_bound_bits: int | None = None,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """A hashrate step against the retarget rule, at mesh level: the
    chain runs an opt-in ``RetargetRule(window, spacing)``; the
    scenario drives block finds at the interval the CURRENT difficulty
    and a stepped hashrate imply (``spacing * 2^(d - d0) / h`` — a
    ``shock_factor`` x hashrate jump finds blocks that much faster
    until difficulty catches up), holds the shock for
    ``shock_windows``, then drops the hashrate back.

    The oscillation question is whether the clamp
    (core/retarget.py ``adjusted``: at most ``max_adjust`` bits per
    retarget) bounds the overshoot.  ok asserts, from the sealed
    headers every node converged on: (a) every retarget moved at most
    ``max_adjust`` bits — the clamp held THROUGH assembly and
    validation, not just in the unit rule; (b) peak difficulty never
    exceeded the shock equilibrium ``d0 + log2(shock_factor)`` by more
    than ``overshoot_bound_bits`` (default: ``max_adjust``); (c) the
    DOWNWARD swing is clamp-bounded too — a shock deep enough to hit
    the ``max_step`` timestamp cap leaves the chain clock lagging the
    wall, and the catch-up reads as inflated spans that drag
    difficulty BELOW base on the way back (the oscillation this
    scenario exists to measure): the undershoot must stay within
    ``max_adjust`` bits of base; (d) the rule actually responded
    (peak ≥ 2 bits over base — the load-bearing control;
    ``overshoot_bound_bits=-3`` is the impossible-bound control test);
    (e) after recovery the difficulty returns to within one bit of
    base and holds for the final window.  tests/test_retarget.py pins
    the same clamp at the unit level (the satellite)."""
    import math

    from p1_tpu.core.retarget import RetargetRule

    rule = RetargetRule(window, spacing)
    if overshoot_bound_bits is None:
        overshoot_bound_bits = rule.max_adjust
    shock_bits = round(math.log2(shock_factor))
    base_difficulty = difficulty
    net = SimNet(seed=seed, difficulty=base_difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0x4E7A)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                retarget_window=window,
                target_spacing=spacing,
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"

        phases = (
            [1] * (warm_windows * window)
            + [shock_factor] * (shock_windows * window)
            + [1] * (recovery_windows * window)
        )
        for h_rate in phases:
            d = miner.chain.required_difficulty(miner.chain.tip_hash)
            dt = spacing * (2.0 ** (d - base_difficulty)) / h_rate
            await net.mine_on(miner, spacing_s=dt)
        final_height = len(phases)
        settled = await net.run_until(
            lambda: net.converged() and min(net.heights()) == final_height,
            180, step=0.25, wall_limit_s=wall_limit_s,
        )

        chain = miner.chain
        series = [
            chain._block_at(chain.main_hash_at(h)).header.difficulty
            for h in range(1, chain.height + 1)
        ]
        deltas = [
            series[i] - series[i - 1] for i in range(1, len(series))
        ]
        clamp_held = all(abs(d) <= rule.max_adjust for d in deltas)
        peak = max(series)
        trough = min(series[warm_windows * window :])
        eq_shock = base_difficulty + shock_bits
        tail = series[-window:]
        report = _report(
            net, "retarget-shock", t0,
            window=window,
            spacing=spacing,
            max_adjust=rule.max_adjust,
            shock_factor=shock_factor,
            difficulty_series=series,
            base_difficulty=base_difficulty,
            peak_difficulty=peak,
            trough_difficulty=trough,
            shock_equilibrium=eq_shock,
            overshoot_bits=peak - eq_shock,
            undershoot_bits=base_difficulty - trough,
            overshoot_bound_bits=overshoot_bound_bits,
            retarget_clamp_held=clamp_held,
            responded=peak >= base_difficulty + 2,
            recovered=max(tail) <= base_difficulty + 1
            and min(tail) >= max(1, base_difficulty - 1),
            settled=settled,
        )
        report["ok"] = bool(
            settled
            and report["converged"]
            and report["ledger_conserved"]
            and clamp_held
            and report["responded"]
            and peak - eq_shock <= overshoot_bound_bits
            and base_difficulty - trough <= rule.max_adjust
            and report["recovered"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- snapshot cartel ------------------------------------------------------


def snapshot_cartel(
    nodes: int = 12,
    cartel: int = 3,
    joiners: int = 2,
    chain_blocks: int = 10,
    liar_height: int = 8,
    interval: int = 4,
    seed: int = 0,
    difficulty: int = 8,
    honest_extra_blocks: int = 4,
    verdict_timeout_vs: float = 300.0,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Coordinated lying-snapshot servers vs the PR-9 divergence
    machinery: ``cartel`` hostile peers serve the SAME internally
    consistent lying snapshot (one shared fork with forged balances,
    its HELLO advertising a far-ahead tip), and every joiner's peer
    list puts
    the whole cartel ahead of its one honest contact — so snapshot
    failover lands on another liar telling the same story.

    The containment path under test: each joiner adopts a cartel
    snapshot (ASSUMED — the cartel's HELLO advertises a far-ahead tip
    so its snapshot out-bids the honest mesh's), background
    revalidation replays the cartel's own history, the state root
    refuses to reproduce → divergence → quarantine + server demotion →
    genesis IBD onto the honest chain.
    The cartel's fork is a VALID chain but carries LESS work than the
    honest one (``liar_height < chain_blocks``) — deliberately: a
    "cartel" whose fork outweighs the honest chain is a majority-work
    attacker, and no snapshot machinery can (or should) overrule the
    heaviest-chain rule against majority work.  What the snapshot
    plane owes is exactly this: lying STATE never survives, no matter
    how many coordinated servers repeat it.

    ok = every joiner saw ≥1 divergence and 0 flips, ended
    fully-validated on the honest tip (fooled == 0), the honest mesh
    RETAINED ITS OWN HISTORY (the pre-join block at ``chain_blocks``
    is still every node's main chain — the capture detector), and the
    mesh converged with the ledger conserved.  The control test hands
    the cartel a heavier fork (``liar_height > chain_blocks`` with
    ``honest_extra_blocks=0``): the mesh is captured, the history
    anchor breaks, and ok goes false — proving the assertion detects
    exactly the takeover it exists to catch."""
    from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0xCA47)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                snapshot_interval=interval,
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(chain_blocks):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == chain_blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-join"
        honest_anchor = miner.chain.main_hash_at(chain_blocks)

        # ONE shared lying chain: the cartel's consistency is the
        # attack — a joiner that fails over cross-checks nothing.
        lying_chain = make_blocks(
            liar_height, difficulty, miner_id="cartel"
        )
        servers = []
        for k in range(cartel):
            src = f"66.9.9.{k}"
            hp = HostilePeer(
                lying_chain,
                # Lying is free: the cartel advertises a far-ahead tip
                # (so joiners prefer its snapshot over the honest
                # mesh's) while serving its short fork and the forged
                # state — the snapshot plane must catch the STATE lie
                # regardless of what the HELLO claimed.
                plan=FaultPlan(
                    snapshot_lie="balance",
                    hello_height=chain_blocks + 16,
                ),
                transport=net.net.host(src),
                host=src,
                rng=random.Random(seed * 37 + k),
            )
            await hp.start()
            servers.append(hp)

        joined = []
        for j in range(joiners):
            peers = [
                f"{hp.host}:{hp.port}" for hp in servers
            ] + [hosts[j % len(hosts)]]
            node = await net.add_node(
                name=f"10.99.8.{j}",
                peers=peers,
                snapshot_sync=True,
                snapshot_min_lead=2,
                snapshot_interval=interval,
            )
            joined.append(node)
            await asyncio.sleep(1.0)

        verdicts = await net.run_until(
            lambda: all(
                n.validation_state == "validated" and n._bg_chain is None
                for n in joined
            ),
            verdict_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        # Honest hashrate outruns the cartel's static fork.
        for _ in range(honest_extra_blocks):
            await net.mine_on(miner, spacing_s=1.0)
        settled = await net.run_until(
            net.converged, 180, step=0.25, wall_limit_s=wall_limit_s
        )

        honest_tip = miner.chain.tip_hash
        history_kept = all(
            net.nodes[h].chain.main_hash_at(chain_blocks) == honest_anchor
            for h in hosts
        )
        fooled = sum(
            1
            for n in joined
            if n.chain.tip_hash != honest_tip
            or n.validation_state != "validated"
        )
        divergences = sum(
            n.metrics.snapshot_divergences for n in joined
        )
        flips = sum(n.metrics.snapshot_flips for n in joined)
        cartel_hosts = {hp.host for hp in servers}
        cartel_scored = sum(
            1
            for n in joined
            for h in sorted(cartel_hosts)
            if h in n._violations
        )
        report = _report(
            net, "snapshot-cartel", t0,
            cartel=cartel,
            joiners=joiners,
            liar_height=liar_height,
            verdicts=verdicts,
            divergences=divergences,
            flips=flips,
            fooled=fooled,
            cartel_servers_scored=cartel_scored,
            honest_history_kept=history_kept,
            honest_extra_blocks=honest_extra_blocks,
            settled=settled,
        )
        report["ok"] = bool(
            verdicts
            and settled
            and report["converged"]
            and report["ledger_conserved"]
            and divergences >= joiners
            and flips == 0
            and fooled == 0
            and history_kept
        )
        for hp in servers:
            await hp.stop()
        await net.stop_all()
        return report

    return net.run(main())


# -- version-bits activation ---------------------------------------------


def version_activation(
    nodes: int = 8,
    seed: int = 0,
    difficulty: int = 8,
    vb_window: int = 8,
    vb_threshold: int = 6,
    straggler_per_window: int = 2,
    extra_windows: int = 1,
    fork_bound: int = 0,
    margin: int = 0,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """BIP9-analog version-bits activation on a MIXED-version mesh: a
    majority of round-20 nodes signal ``feature-x`` on bit 0 while one
    straggler runs the legacy table — it mines literal ``version=1``
    forever and has never heard of deployments.  The straggler keeps a
    deterministic slice of the hashrate (``straggler_per_window`` block
    slots per retarget-window), so the signaling window carries exactly
    ``vb_window - straggler_per_window`` signaling blocks — at or above
    ``vb_threshold`` by construction — and the deployment must walk
    DEFINED → STARTED → LOCKED_IN → ACTIVE at the predicted heights.

    The no-fork bound under test: header ``version`` is NOT consensus
    here (exactly as in Bitcoin's soft-fork deployments pre-enforcement),
    so the mixed mesh must never diverge — not while the stragglers'
    legacy blocks interleave with signaling ones pre-activation, not at
    the LOCKED_IN boundary, and not after ACTIVE clears the signal bit.
    ok asserts persistent-fork observations ≤ ``fork_bound + margin``
    (``margin=-1`` is the impossible-bound control) plus the structural
    bits: the straggler really mined on both sides of activation and its
    blocks were accepted by everyone, and the signaling window really
    carried ≥ threshold signaling headers."""
    assert vb_window - straggler_per_window >= vb_threshold, (
        "shape can never lock in: raise the signaling share"
    )
    start = vb_window  # first full window: heights [W, 2W)
    deploy = (("feature-x", 0, start, vb_window * 16),)
    # Ladder prediction, in tip heights at window boundaries: the
    # window [W, 2W) is STARTED and is the one whose signal count is
    # judged, so LOCKED_IN begins at 2W and ACTIVE at 3W.
    activation_height = 3 * vb_window
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    STRAGGLER = "straggler"

    async def main():
        from p1_tpu.chain.versionbits import TOP_BITS, signals

        rng = random.Random(seed ^ 0xB1B9)
        for i in range(nodes - 1):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                miner_id=f"signal-{i}",
                deployments=deploy,
                vb_window=vb_window,
                vb_threshold=vb_threshold,
            )
        hosts = list(net.nodes)
        rep = net.nodes[hosts[0]]
        # The straggler joins the same mesh as a full peer — the point
        # is precisely that nothing about deployments is negotiated.
        straggler = await net.add_node(
            peers=[hosts[0], hosts[-1]], miner_id=STRAGGLER
        )
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"

        dep_report = (
            lambda: rep.versionbits.states_report(rep.chain)["feature-x"]
        )
        ladder: dict[int, str] = {0: dep_report()["state"]}
        versions = {"signaling": set(), "straggler": set()}
        stats = {
            "straggler_finds": 0,
            "straggler_finds_pre_activation": 0,
            "signal_finds": 0,
            "fork_checks": 0,
            "forks_observed": 0,
        }

        # Versionbits windows are ANCHORED at multiples of vb_window
        # (genesis fills slot 0 of window 0), so the straggler's slots
        # are sampled per anchored window — its share of any window the
        # threshold judges is exact, which is what makes the lock-in
        # deterministic rather than a coin flip on the seed.
        target_height = vb_window * (3 + extra_windows) - 1
        window, slots = -1, set()
        for h in range(1, target_height + 1):
            if h // vb_window != window:
                window = h // vb_window
                slots = set(
                    rng.sample(range(vb_window), straggler_per_window)
                )
            if h % vb_window in slots:
                miner, side = straggler, "straggler"
                stats["straggler_finds"] += 1
                if h < activation_height:
                    stats["straggler_finds_pre_activation"] += 1
            else:
                miner = net.nodes[hosts[h % (nodes - 1)]]
                side = "signaling"
                stats["signal_finds"] += 1
            blk = await net.mine_on(miner, spacing_s=1.0)
            versions[side].add(blk.header.version)
            assert await net.run_until(
                lambda: min(net.heights()) >= h,
                60, step=0.25, wall_limit_s=wall_limit_s,
            ), f"block {h} never propagated"
            if h % vb_window == 0:
                # Window boundary: a persistent tip split here is
                # exactly the fork the scenario exists to rule out.
                stats["fork_checks"] += 1
                if not await net.run_until(
                    net.converged, 60, step=0.25,
                    wall_limit_s=wall_limit_s,
                ):
                    stats["forks_observed"] += 1
                ladder[h] = dep_report()["state"]

        settled = await net.run_until(
            net.converged, 120, step=0.25, wall_limit_s=wall_limit_s
        )

        # Chain autopsy: whose coinbases landed, and did the STARTED
        # window really carry enough signaling headers.
        chain = rep.chain
        straggler_on_chain = {"pre": 0, "post": 0}
        signal_bit_in_started_window = 0
        for h in range(1, chain.height + 1):
            block = chain._block_at(chain.main_hash_at(h))
            if block.txs[0].recipient == STRAGGLER:
                side = "pre" if h < activation_height else "post"
                straggler_on_chain[side] += 1
            if start <= h < 2 * vb_window and signals(
                block.header.version, 0
            ):
                signal_bit_in_started_window += 1

        ladder_ok = (
            ladder.get(vb_window) == "started"
            and ladder.get(2 * vb_window) == "locked_in"
            and ladder.get(3 * vb_window) == "active"
        )
        # Every signaling node must agree the bit is ACTIVE; the
        # straggler's report is empty — it has no deployments to state.
        states_agree = all(
            net.nodes[h].versionbits.states_report(net.nodes[h].chain)[
                "feature-x"
            ]["state"] == "active"
            for h in hosts
        ) and straggler.versionbits.states_report(straggler.chain) == {}

        bound = fork_bound + margin
        containment_held = stats["forks_observed"] <= bound
        report = _report(
            net, "version-activation", t0,
            vb_window=vb_window,
            vb_threshold=vb_threshold,
            activation_height=activation_height,
            ladder={str(h): s for h, s in sorted(ladder.items())},
            ladder_ok=ladder_ok,
            states_agree=states_agree,
            signal_bit_in_started_window=signal_bit_in_started_window,
            straggler_blocks_pre_activation=straggler_on_chain["pre"],
            straggler_blocks_post_activation=straggler_on_chain["post"],
            signaling_versions=sorted(
                f"0x{v:08x}" for v in versions["signaling"]
            ),
            straggler_versions=sorted(
                f"0x{v:08x}" for v in versions["straggler"]
            ),
            fork_bound_effective=bound,
            containment_held=containment_held,
            settled=settled,
            **stats,
        )
        report["ok"] = bool(
            settled
            and report["converged"]
            and report["ledger_conserved"]
            and containment_held
            and ladder_ok
            and states_agree
            # The mix must actually have run, or the no-fork claim is
            # vacuous: legacy blocks on BOTH sides of activation, all
            # accepted; the signaling window really cleared threshold;
            # the straggler never emitted anything but literal 1.
            and straggler_on_chain["pre"] > 0
            and straggler_on_chain["post"] > 0
            and signal_bit_in_started_window >= vb_threshold
            and versions["straggler"] == {1}
            and TOP_BITS | 1 in versions["signaling"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- registry / CLI entry ------------------------------------------------

def soak(
    seed: int = 0,
    difficulty: int = 8,
    days: float = 7.0,
    nodes: int = 5,
    **kwargs,
) -> dict:
    """Longevity soak: ≥1 virtual WEEK of mesh life (node/chaos.py
    ``longevity_soak``) — steady mining, recurring fault/heal cycles
    across every injector, wallet traffic — with the leak invariants
    (RSS, ban tables, caches, task counts, retry counters) asserted at
    quiesce.  Registered here so `p1 sim soak --seed N` is the one-flag
    repro like every other scenario."""
    from p1_tpu.node.chaos import longevity_soak

    return longevity_soak(
        seed=seed, difficulty=difficulty, days=days, nodes=nodes, **kwargs
    )


def fleet_failover(
    replicas: int = 3,
    sessions: int = 6,
    chain_blocks: int = 4,
    post_blocks: int = 3,
    seed: int = 0,
    difficulty: int = 8,
    wall_limit_s: float | None = 240.0,
) -> dict:
    """The kill-one-replica proof (round 22), deterministic form: N
    serving replicas on one chain, ``sessions`` wallet watchers whose
    ReplicaSets spread subscriptions across them (distinct
    ``spread_key`` per session), one replica killed MID-PUSH — every
    wallet must fail over at its verified cursor and end the run with a
    gap-free, fully matched confirmation stream: zero missed
    confirmations, by construction of the invariant, not by luck.

    ``ok`` requires: subscriptions actually spread (>= 2 distinct
    active targets before the kill with >= 2 live replicas), at least
    one session failed over, every session's height stream is
    contiguous with every event matched (each block pays the watched
    wallet), and the mesh converged with the ledger conserved.  The
    wall-clock fleet figure (notify p95 under kill, queue depth) is
    ``benchmarks/wallet_plane.py``'s job — this scenario pins the
    CORRECTNESS half in virtual time, replayable by seed."""
    from p1_tpu.node import client

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    WALLET = "fleet-wallet"

    async def main():
        rng = random.Random(seed ^ 0xF1EE7)
        for i in range(replicas):
            # Every node mines to the watched wallet: any block from
            # any survivor is a confirmation the watchers must see.
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 2)],
                miner_id=WALLET,
            )
        hosts = list(net.nodes)
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(chain_blocks):
            await net.mine_on(net.nodes[hosts[0]], spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == chain_blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-watch"

        targets = [(h, NODE_PORT) for h in hosts]
        sets = [
            client.ReplicaSet(targets, spread_key=k) for k in range(sessions)
        ]
        streams: list[list[dict]] = [[] for _ in range(sessions)]
        errors: list[str | None] = [None] * sessions

        async def _watch(k: int) -> None:
            transport = net.net.host(f"77.9.0.{k}")
            try:
                async for ev in client.watch(
                    hosts[0], NODE_PORT, [WALLET], difficulty,
                    replica_set=sets[k], transport=transport,
                    cross_check_every=0, reconnect_delay_s=0.5,
                    max_session_failures=None,
                ):
                    streams[k].append(ev)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — judged in the report
                errors[k] = f"{type(e).__name__}: {e}"

        tasks = [asyncio.create_task(_watch(k)) for k in range(sessions)]
        # All ears first (a subscription that lands after the block
        # anchors at the NEW tip and owes nothing for it), then one
        # block that every session must see pushed.
        assert await net.run_until(
            lambda: sum(
                n.subscriptions.snapshot()["live"]
                for n in net.nodes.values()
            ) >= sessions,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "sessions never all subscribed"
        await net.mine_on(net.nodes[hosts[0]], spacing_s=1.0)
        assert await net.run_until(
            lambda: all(streams[k] for k in range(sessions)),
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "not every session saw the pre-kill block"

        actives = [s.active for s in sets if s.active is not None]
        spread = len(set(actives))
        # The directed kill: the replica carrying the most sessions.
        tally: dict[str, int] = {}
        for a in actives:
            tally[a[0]] = tally.get(a[0], 0) + 1
        victim = max(sorted(tally), key=lambda h: tally[h])
        riders = tally[victim]
        await net.crash_node(victim, torn=0)
        survivor = next(h for h in hosts if h != victim)
        for _ in range(post_blocks):
            await net.mine_on(net.nodes[survivor], spacing_s=1.0)
        final_h = net.nodes[survivor].chain.height
        settled = await net.run_until(
            lambda: all(
                streams[k] and streams[k][-1]["height"] >= final_h
                for k in range(sessions)
            ),
            300, step=0.25, wall_limit_s=wall_limit_s,
        )
        for task in tasks:
            task.cancel()
        for task in tasks:
            try:
                await task
            except asyncio.CancelledError:
                pass

        gap_free = all(
            [ev["height"] for ev in s]
            == list(range(s[0]["height"], s[0]["height"] + len(s)))
            for s in streams if s
        )
        all_matched = all(ev["matched"] for s in streams for ev in s)
        failovers = sum(s.failovers for s in sets)
        report = _report(
            net, "fleet-failover", t0,
            repro_flags=f"--replicas {replicas} --sessions {sessions}",
            replicas=replicas,
            sessions=sessions,
            victim=victim,
            victim_riders=riders,
            spread=spread,
            failovers=failovers,
            gap_free=gap_free,
            all_matched=all_matched,
            missed_confirmations=0 if (gap_free and all_matched) else 1,
            errors=[e for e in errors if e],
        )
        report["ok"] = bool(
            settled
            and gap_free
            and all_matched
            and not any(errors)
            and failovers >= riders >= 1
            and (spread >= 2 or replicas < 2 or sessions < 2)
            and report["ledger_conserved"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- relay bandwidth budget: flood vs set reconciliation ------------------


def _tx_plane_bytes(node) -> int:
    """Bytes this node has SENT on the transaction plane: TX pushes plus
    every reconciliation frame (node.py ``_RELAY_ACCOUNTING`` families
    ``tx`` + ``recon``).  Blocks, serves, control are excluded — the
    budget under test is tx relay, and nothing else runs during the
    measured storm anyway."""
    rb = node.metrics.relay_bytes()
    return rb.get("tx", 0) + rb.get("recon", 0)


_PROP_BUCKETS_MS = (25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000)


def _prop_histogram(delays_ms: list[float]) -> dict:
    """Fixed-bucket histogram + quantiles of per-(tx, node) propagation
    delays, virtual milliseconds — the per-arm telemetry the A/B report
    carries so a regression shows WHERE the tail moved, not just that
    one number crossed another."""
    buckets = {f"le_{b}ms": 0 for b in _PROP_BUCKETS_MS}
    buckets["inf"] = 0
    s = sorted(delays_ms)
    for d in s:
        for b in _PROP_BUCKETS_MS:
            if d <= b:
                buckets[f"le_{b}ms"] += 1
                break
        else:
            buckets["inf"] += 1

    def pick(q: float) -> float:
        return round(s[min(len(s) - 1, int(q * len(s)))], 1) if s else 0.0

    return {
        "count": len(s),
        "p50_ms": pick(0.50),
        "p95_ms": pick(0.95),
        "max_ms": round(s[-1], 1) if s else 0.0,
        "buckets": buckets,
    }


def relay_budget(
    nodes: int = 16,
    seed: int = 0,
    difficulty: int = 8,
    degree: int = 6,
    senders: int = 4,
    txs_per_sender: int = 48,
    storm_vs: float = 30.0,
    egress_bps: float = 64_000.0,
    recon_interval_s: float = 0.25,
    recon_flood_degree: int = 0,
    min_reduction: float = 5.0,
    wall_limit_s: float | None = 420.0,
) -> dict:
    """THE tentpole A/B (round 23): the identical mesh, the identical
    seeded tx storm, run twice — arm one floods transactions (the
    pre-round-23 relay), arm two reconciles them (``recon_gossip``) —
    and the report holds both arms' per-link byte totals and propagation
    histograms side by side.

    Every host sits behind a shared ``egress_bps`` uplink (the netsim
    per-host shaping this round added): that is the physical budget
    flooding actually spends, because a node that pushes a tx to
    ``degree`` neighbors serializes ``degree`` copies through ONE access
    link.  The recon arm runs spine-less (``recon_flood_degree=0``, the
    bandwidth-optimal configuration: every tx push is diff-driven, so
    nothing is ever sent to a peer that already has it) and must win on
    BOTH axes at once — bytes AND latency — because the flood arm's
    duplicates are what saturate the shared uplinks.

    ok = tx-plane bytes per transaction drop by at least
    ``min_reduction`` (the ISSUE's >=5x budget) AND the recon arm's
    propagation p95 is equal-or-better — efficiency may not be bought
    with latency.  An absurd ``min_reduction`` (the impossible-bound
    control, pinned by tests/test_scenarios.py) must fail."""
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import BLOCK_REWARD, Transaction

    assert txs_per_sender * 2 <= 2 * BLOCK_REWARD, (
        "storm shape exceeds the two-coinbase wallet budget"
    )
    total_txs = senders * txs_per_sender
    wallets = [
        Keypair.from_seed_text(f"p1-relay-{seed}-{k}") for k in range(senders)
    ]
    payee = Keypair.from_seed_text(f"p1-relay-payee-{seed}")
    genesis = genesis_hash(difficulty)
    t0 = time.monotonic()

    def arm(recon: bool) -> dict:
        net = SimNet(
            seed=seed,
            difficulty=difficulty,
            default_profile=LinkProfile(latency_s=0.01, jitter_s=0.002),
        )

        async def main():
            rng = random.Random(seed ^ 0x3E1A)
            for i in range(nodes):
                await net.add_node(
                    peers=[
                        net.host_name(j)
                        for j in _topology_peers(rng, i, degree)
                    ],
                    recon_gossip=recon,
                    recon_interval_s=recon_interval_s,
                    recon_flood_degree=recon_flood_degree,
                    miner_id="pool",
                )
            hosts = list(net.nodes)
            miner = net.nodes[hosts[0]]
            assert await net.run_until(
                net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
            ), "mesh never formed"
            # Two coinbases per sender wallet: budget for 48 amount-1
            # fee-1 transfers each.
            for w in wallets:
                for _ in range(2):
                    miner.miner_id = w.account
                    await net.mine_on(miner, spacing_s=1.0)
            miner.miner_id = "pool"
            fund_height = miner.chain.height
            assert await net.run_until(
                lambda: net.converged() and min(net.heights()) == fund_height,
                60, step=0.25, wall_limit_s=wall_limit_s,
            ), "mesh never converged post-funding"

            # The uplinks close AFTER funding: the storm is the measured
            # phase, and block sync shouldn't pay the shaped price.
            for h in hosts:
                net.net.host_egress[h] = egress_bps
            base_plane = sum(_tx_plane_bytes(n) for n in net.nodes.values())
            base_links = dict(net.net.link_bytes)
            submits: dict[bytes, tuple[str, float]] = {}

            async def sender(k: int) -> None:
                host = hosts[(k * nodes) // senders]
                node, w = net.nodes[host], wallets[k]
                gap = storm_vs / txs_per_sender
                for s in range(txs_per_sender):
                    await asyncio.sleep(gap)
                    tx = Transaction.transfer(
                        w, payee.account, 1, 1, s, chain=genesis
                    )
                    submits[tx.txid()] = (host, net.clock.now)
                    await node.submit_tx(tx)

            await asyncio.gather(*(sender(k) for k in range(senders)))
            want = set(submits)
            delivered = await net.run_until(
                lambda: all(
                    want <= n.tx_seen_at.keys()
                    for n in net.nodes.values()
                ),
                180, step=0.25, wall_limit_s=wall_limit_s,
            )
            # Measure the instant delivery completes: recon idle rounds
            # up to here are honestly charged to the recon arm.
            plane = sum(_tx_plane_bytes(n) for n in net.nodes.values())
            delays_ms = [
                1000.0 * (n.tx_seen_at[txid] - t_sub)
                for txid, (origin, t_sub) in submits.items()
                for h, n in net.nodes.items()
                if h != origin and txid in n.tx_seen_at
            ]
            link_deltas = [
                total - base_links.get(key, 0)
                for key, total in net.net.link_bytes.items()
            ]
            recon_stats = {
                "rounds": sum(
                    n.metrics.recon_rounds for n in net.nodes.values()
                ),
                "success": sum(
                    n.metrics.recon_success for n in net.nodes.values()
                ),
                "fallbacks": sum(
                    n.metrics.recon_fallbacks for n in net.nodes.values()
                ),
                "txs_reconciled": sum(
                    n.metrics.txs_reconciled for n in net.nodes.values()
                ),
            }
            out = {
                "arm": "recon" if recon else "flood",
                "delivered": delivered,
                "tx_plane_bytes": plane - base_plane,
                "bytes_per_tx": round((plane - base_plane) / total_txs, 1),
                "link_bytes_storm_total": sum(link_deltas),
                "link_bytes_storm_max": max(link_deltas, default=0),
                "propagation": _prop_histogram(delays_ms),
                "recon": recon_stats,
            }
            if recon:
                # The framework report (converged / conserved / digest)
                # must read the nodes BEFORE stop_all pops them.
                out["_base"] = _report(
                    net, "relay-budget", t0,
                    repro_flags=f"--nodes {nodes}",
                )
            else:
                out["trace_digest"] = net.trace_digest()
            await net.stop_all()
            return out

        return net.run(main())

    flood = arm(recon=False)
    recon = arm(recon=True)
    base = recon.pop("_base")
    recon["trace_digest"] = base["trace_digest"]
    reduction = (
        flood["bytes_per_tx"] / recon["bytes_per_tx"]
        if recon["bytes_per_tx"]
        else float("inf")
    )
    report = dict(
        base,
        total_txs=total_txs,
        egress_bps=egress_bps,
        flood=flood,
        recon=recon,
        relay_bytes_per_tx={
            "flood": flood["bytes_per_tx"], "recon": recon["bytes_per_tx"]
        },
        reduction=round(reduction, 2),
        min_reduction=min_reduction,
    )
    report["ok"] = bool(
        flood["delivered"]
        and recon["delivered"]
        and reduction >= min_reduction
        # Equal-or-better: the byte win may not cost latency.
        and recon["propagation"]["p95_ms"] <= flood["propagation"]["p95_ms"]
        and recon["recon"]["success"] > 0
    )
    return report


# -- reconciliation overload: the flood fallback --------------------------


def recon_fallback(
    nodes: int = 5,
    seed: int = 0,
    difficulty: int = 8,
    burst: int = 80,
    recon_interval_s: float = 1.0,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Overload the sketch: one node takes a ``burst`` of transactions
    (> the codec's MAX_CAPACITY=64) inside a single reconciliation
    interval, with the flood spine OFF (``recon_flood_degree=0``) so the
    whole burst must ride one round.  The set difference exceeds any
    sketch the responder can serve, decode fails — DETECTED, by the
    codec's verification syndrome, not mis-decoded — and the initiator's
    RECONCILDIFF(failure) makes both ends flood their frozen windows.

    ok = every burst tx reaches every node anyway (flood is the pressure
    valve), at least one fallback was counted, and NO link was demoted —
    overload is congestion, not misbehavior, and one failed round must
    not cost a link its recon plane."""
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import BLOCK_REWARD, Transaction
    from p1_tpu.node.reconcile import MAX_CAPACITY

    assert burst > MAX_CAPACITY, "burst must exceed sketch capacity"
    coinbases = (2 * burst + BLOCK_REWARD - 1) // BLOCK_REWARD
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    wallet = Keypair.from_seed_text(f"p1-burst-{seed}")
    payee = Keypair.from_seed_text(f"p1-burst-payee-{seed}")

    async def main():
        rng = random.Random(seed ^ 0xFA11)
        for i in range(nodes):
            await net.add_node(
                peers=[
                    net.host_name(j) for j in _topology_peers(rng, i, 2)
                ],
                recon_gossip=True,
                recon_interval_s=recon_interval_s,
                recon_flood_degree=0,
                miner_id="pool",
            )
        hosts = list(net.nodes)
        origin = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(coinbases):
            origin.miner_id = wallet.account
            await net.mine_on(origin, spacing_s=1.0)
        origin.miner_id = "pool"
        fund_height = origin.chain.height
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == fund_height,
            60, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged post-funding"

        genesis = genesis_hash(difficulty)
        # The whole burst lands at ONE virtual instant: submit_tx never
        # sleeps, so no reconciliation tick can slice the burst into
        # decodable halves.
        txids = []
        for s in range(burst):
            tx = Transaction.transfer(
                wallet, payee.account, 1, 1, s, chain=genesis
            )
            txids.append(tx.txid())
            await origin.submit_tx(tx)
        want = set(txids)
        delivered = await net.run_until(
            lambda: all(
                want <= n.tx_seen_at.keys() for n in net.nodes.values()
            ),
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        fallbacks = sum(
            n.metrics.recon_fallbacks for n in net.nodes.values()
        )
        demotions = sum(
            n.metrics.recon_demotions for n in net.nodes.values()
        )
        report = _report(
            net, "recon-fallback", t0,
            repro_flags=f"--burst {burst}",
            burst=burst,
            delivered=delivered,
            recon_fallbacks=fallbacks,
            recon_demotions=demotions,
            recon_rounds=sum(
                n.metrics.recon_rounds for n in net.nodes.values()
            ),
        )
        report["ok"] = bool(
            delivered
            and report["converged"]
            and report["ledger_conserved"]
            and fallbacks >= 1
            and demotions == 0
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- sketch poisoning: the recon plane's byzantine containment ------------


def recon_poison(
    nodes: int = 8,
    seed: int = 0,
    difficulty: int = 8,
    honest_txs: int = 24,
    storm_vs: float = 30.0,
    recon_interval_s: float = 0.5,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """A ``sketch_poisoner`` (node/byzantine.py) camps a listening
    address; the victim node dials it as a configured peer, so the
    poisoner sits on the victim's OUTBOUND recon rotation — garbage
    sketches fail every round the victim initiates there, fabricated
    RECONCILDIFFs promise short ids nothing maps to, and REQRECON/GETTX
    spam burns responder serves.

    The containment under test: the victim burns RECON_DEMOTE_FAILURES
    rounds, demotes the link to plain flood (``recon_demotions``), and
    honest relay NEVER stalls — every honest tx reaches every honest
    node while reconciliation keeps succeeding on honest links.  ok
    asserts exactly that, plus that the poisoner really served garbage
    (its stats say so) and the honest mesh stayed converged."""
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.byzantine import new_stats, sketch_poisoner

    POISON_HOST = "66.6.0.66"
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    wallet = Keypair.from_seed_text(f"p1-poison-{seed}")
    payee = Keypair.from_seed_text(f"p1-poison-payee-{seed}")
    stats = new_stats()

    async def main():
        rng = random.Random(seed ^ 0x9013)
        deadline = net.clock.wall() + storm_vs + 120
        poison_task = asyncio.ensure_future(
            sketch_poisoner(
                POISON_HOST, NODE_PORT, difficulty, deadline, None,
                stats, transport=net.net.host(POISON_HOST),
            )
        )
        dials = 0
        for i in range(nodes):
            peers = [net.host_name(j) for j in _topology_peers(rng, i, 3)]
            if i == nodes - 1:
                peers.append(POISON_HOST)  # the victim dials the trap
            dials += len(peers)
            await net.add_node(
                peers=peers,
                recon_gossip=True,
                recon_interval_s=recon_interval_s,
                miner_id="pool",
            )
        hosts = list(net.nodes)
        victim = net.nodes[hosts[-1]]
        # links_up can't apply: the poisoner end registers no _Peer, so
        # the poisoner dial contributes 1 registration, not 2.
        assert await net.run_until(
            lambda: sum(n.peer_count() for n in net.nodes.values())
            >= 2 * (dials - 1) + 1,
            60, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never formed"
        miner = net.nodes[hosts[0]]
        for _ in range(2):
            miner.miner_id = wallet.account
            await net.mine_on(miner, spacing_s=1.0)
        miner.miner_id = "pool"
        fund_height = miner.chain.height
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == fund_height,
            60, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged post-funding"

        genesis = genesis_hash(difficulty)
        txids = []
        gap = storm_vs / honest_txs
        for s in range(honest_txs):
            await asyncio.sleep(gap)
            tx = Transaction.transfer(
                wallet, payee.account, 1, 1, s, chain=genesis
            )
            txids.append(tx.txid())
            await net.nodes[hosts[s % (nodes - 1)]].submit_tx(tx)
        want = set(txids)
        delivered = await net.run_until(
            lambda: all(
                want <= n.tx_seen_at.keys() for n in net.nodes.values()
            ),
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        poison_task.cancel()
        try:
            await poison_task
        except asyncio.CancelledError:
            pass
        honest_success = sum(
            n.metrics.recon_success for n in net.nodes.values()
        )
        report = _report(
            net, "recon-poison", t0,
            honest_txs=honest_txs,
            delivered=delivered,
            victim_demotions=victim.metrics.recon_demotions,
            victim_fallbacks=victim.metrics.recon_fallbacks,
            honest_recon_success=honest_success,
            poisoner_attacks=dict(stats["attacks"]),
        )
        report["ok"] = bool(
            delivered
            and report["converged"]
            and report["ledger_conserved"]
            # The attack really ran: garbage sketches were served and
            # the victim paid with demotion, not with stalled relay.
            and stats["attacks"].get("garbage_sketch", 0) >= 1
            and victim.metrics.recon_demotions >= 1
            # ... while reconciliation kept working between honest ends.
            and honest_success > 0
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- mixed-version mesh: recon activates by version bits ------------------


def recon_mixed(
    nodes: int = 8,
    seed: int = 0,
    difficulty: int = 8,
    vb_window: int = 8,
    vb_threshold: int = 6,
    txs_per_phase: int = 8,
    recon_interval_s: float = 0.5,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Recon rides PR 17's evolution contract: upgraded nodes carry a
    "txrecon" version-bits deployment AND ``recon_gossip=True``, one
    straggler runs the legacy table with flood-only relay.  Before the
    deployment is ACTIVE the upgraded nodes must keep flooding (zero
    reconciliation rounds — the wire stays the shared dialect); after
    the miners' signals lock it in and activate it, reconciliation
    starts among upgraded links while the straggler keeps receiving
    every tx by flood and by answering sketches it never initiates.

    ok = both phases' txs reach EVERY node including the straggler, no
    rounds ran pre-activation, rounds succeed post-activation, and the
    mixed mesh never forked."""
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction

    deploy = (("txrecon", 0, vb_window, vb_window * 16),)
    activation_height = 3 * vb_window
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    wallet = Keypair.from_seed_text(f"p1-mixed-{seed}")
    payee = Keypair.from_seed_text(f"p1-mixed-payee-{seed}")

    async def main():
        rng = random.Random(seed ^ 0x717C)
        for i in range(nodes - 1):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)],
                recon_gossip=True,
                recon_interval_s=recon_interval_s,
                deployments=deploy,
                vb_window=vb_window,
                vb_threshold=vb_threshold,
                miner_id="pool",
            )
        hosts = list(net.nodes)
        straggler = await net.add_node(peers=[hosts[0], hosts[-1]])
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        miner = net.nodes[hosts[0]]
        for _ in range(2):
            miner.miner_id = wallet.account
            await net.mine_on(miner, spacing_s=1.0)
        miner.miner_id = "pool"
        genesis = genesis_hash(difficulty)

        async def submit_wave(first_seq: int) -> set[bytes]:
            ids = set()
            for s in range(first_seq, first_seq + txs_per_phase):
                tx = Transaction.transfer(
                    wallet, payee.account, 1, 1, s, chain=genesis
                )
                ids.add(tx.txid())
                await net.nodes[hosts[s % (nodes - 1)]].submit_tx(tx)
                await asyncio.sleep(0.5)
            return ids

        # Phase A: pre-activation.  Upgraded nodes have recon configured
        # but the deployment gate holds it shut.
        pre = await submit_wave(0)
        pre_delivered = await net.run_until(
            lambda: all(
                pre <= n.tx_seen_at.keys() for n in net.nodes.values()
            ),
            60, step=0.25, wall_limit_s=wall_limit_s,
        )
        rounds_pre = sum(
            n.metrics.recon_rounds for n in net.nodes.values()
        )

        # Every block an upgraded miner seals signals bit 0; the
        # straggler just follows.  Walk the ladder to ACTIVE.
        while miner.chain.height < activation_height:
            await net.mine_on(
                net.nodes[hosts[miner.chain.height % (nodes - 1)]],
                spacing_s=1.0,
            )
        assert await net.run_until(
            lambda: net.converged()
            and min(net.heights()) >= activation_height,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never reached activation height"
        state = miner.versionbits.states_report(miner.chain)["txrecon"][
            "state"
        ]

        # Phase B: post-activation.  Recon runs among upgraded links;
        # the straggler still sees everything.
        post = await submit_wave(txs_per_phase)
        post_delivered = await net.run_until(
            lambda: all(
                post <= n.tx_seen_at.keys() for n in net.nodes.values()
            ),
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        success_post = sum(
            n.metrics.recon_success for n in net.nodes.values()
        )
        settled = await net.run_until(
            net.converged, 60, step=0.25, wall_limit_s=wall_limit_s
        )
        report = _report(
            net, "recon-mixed", t0,
            activation_state=state,
            activation_height=activation_height,
            pre_delivered=pre_delivered,
            post_delivered=post_delivered,
            recon_rounds_pre_activation=rounds_pre,
            recon_success_post_activation=success_post,
            straggler_txs_seen=len(straggler.tx_seen_at),
        )
        report["ok"] = bool(
            pre_delivered
            and post_delivered
            and settled
            and report["ledger_conserved"]
            and state == "active"
            # The wire contract held: silent pre-activation, live after.
            and rounds_pre == 0
            and success_post > 0
        )
        await net.stop_all()
        return report

    return net.run(main())


SCENARIOS = {
    "partition-heal": partition_heal,
    "flash-crowd": flash_crowd,
    "churn": churn_storm,
    "eclipse": eclipse,
    "wan": wan,
    "snapshot-join": snapshot_join,
    "far-field": far_field,
    "selfish-mining": selfish_mining,
    "fee-spam": fee_spam,
    "retarget-shock": retarget_shock,
    "snapshot-cartel": snapshot_cartel,
    "version-activation": version_activation,
    "fleet-failover": fleet_failover,
    "soak": soak,
    "relay-budget": relay_budget,
    "recon-fallback": recon_fallback,
    "recon-poison": recon_poison,
    "recon-mixed": recon_mixed,
}


def run_scenario(name: str, **kwargs) -> dict:
    """Run one named scenario; unknown kwargs raise TypeError (the CLI
    filters per-scenario flags before calling)."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have: {', '.join(sorted(SCENARIOS))}"
        ) from None
    return fn(**kwargs)
