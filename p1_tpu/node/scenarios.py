"""The scenario corpus: consensus emergent behavior at simulated scale.

The north star asks for "as many scenarios as you can imagine"; this
module is the library that opens — each scenario a deterministic
discrete-event run (node/netsim.py) of REAL ``Node`` instances
(consensus, mempool, governor, supervision, address book — nothing
mocked) that asserts a convergence or containment metric in bounded
*virtual* time.  The Bitcoin-Core lineage names the families:

- **partition-heal** — the mesh splits (600/400 at the flagship scale),
  both sides keep mining, the cut heals, and every node must converge
  to the one heaviest tip with the ledger-sum invariant intact.  This
  scenario found a real propagation gap on its first 1000-node run:
  batch-synced blocks were never re-announced, so regions with no
  direct link across the old cut never converged (node.py
  ``_announce_tip``).
- **flash-crowd** — hundreds of fresh nodes join at once against one
  seed (the thundering-herd IBD); everyone must reach the seed's tip
  even though the seed's MAX_PEERS/MAX_HANDSHAKING caps refuse most of
  the crowd, which must sync through each other instead.
- **churn** — waves of nodes stop and restart (same identity, same
  address) while mining continues; the survivors and the returners must
  converge and conserve.
- **eclipse** — attackers flood a victim's address book from many
  hosts and camp its inbound slots; the tried/new bucket split and the
  per-host ADDR budgets must keep the victim attached to the honest
  mesh and its book bounded.
- **wan** — regions with asymmetric inter-region latency/bandwidth;
  convergence must hold and measured propagation delay must reflect
  the configured geography (the sanity proof that the latency model is
  real, and the rig for propagation studies).

Every report carries ``trace_digest`` — two runs with the same seed
are byte-identical (tests/test_netsim.py asserts it), so any scenario
failure is replayable by seed alone.  `p1 sim` runs these from the
command line and prints the report as one JSON line.
"""

from __future__ import annotations

import asyncio
import random
import time

from p1_tpu.node.netsim import NODE_PORT, LinkProfile, SimNet

__all__ = ["SCENARIOS", "run_scenario"]


def _topology_peers(rng: random.Random, i: int, degree: int) -> list[int]:
    """Backbone + random small-world out-edges for node ``i``: always
    dial ``i-1`` (so any CONTIGUOUS index split leaves both sides
    internally connected — the partition scenario's well-posedness),
    plus ``degree-1`` random earlier nodes for short gossip paths."""
    if i == 0:
        return []
    extra = rng.sample(range(i - 1), min(i - 1, degree - 1))
    return [i - 1, *extra]


def _report(net: SimNet, scenario: str, t0: float, **extra) -> dict:
    from p1_tpu.node.telemetry import propagation_summary_ms

    report = {
        "scenario": scenario,
        "seed": net.seed,
        "nodes": len(net.nodes),
        "virtual_s": round(net.clock.now, 3),
        "wall_s": round(time.monotonic() - t0, 3),
        "events": net.net.events,
        "converged": net.converged(),
        "ledger_conserved": net.ledger_conserved(),
        "heights": {
            "min": min(net.heights()),
            "max": max(net.heights()),
        },
        "reorgs_total": sum(
            n.metrics.reorgs for n in net.nodes.values()
        ),
        # Telemetry timeline (round 14): the nodes' propagation
        # histograms merged, in VIRTUAL milliseconds — what lets a
        # scenario assert a p95 propagation bound instead of bare
        # convergence.  None when telemetry is disabled.
        "telemetry": {
            "propagation": propagation_summary_ms(
                n.telemetry for n in net.nodes.values()
            )
        },
        **extra,
    }
    report["trace_digest"] = net.trace_digest()
    return report


# -- partition-heal ------------------------------------------------------


def partition_heal(
    nodes: int = 1000,
    seed: int = 0,
    split: float = 0.6,
    blocks_major: int = 4,
    blocks_minor: int = 2,
    degree: int = 4,
    difficulty: int = 8,
    heal_timeout_vs: float = 180.0,
    wall_limit_s: float | None = 420.0,
    telemetry: bool = True,
) -> dict:
    """The flagship: mesh splits ``split``/1-``split``, both sides mine,
    the cut heals, one tip wins everywhere.  ok = global convergence at
    the majority chain's height, mass reorgs on the minority side, and
    exact ledger conservation, all inside ``heal_timeout_vs`` virtual
    seconds of the heal.  ``telemetry=False`` disables the nodes'
    latency recording — the trace digest must not move (the round-14
    observer contract; tests/test_telemetry.py runs this scenario both
    ways and compares)."""
    net = SimNet(seed=seed, difficulty=difficulty, telemetry=telemetry)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0x70B0)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, degree)]
            )
        hosts = list(net.nodes)
        assert await net.run_until(
            net.links_up, 60, step=0.25, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        miner_a = net.nodes[hosts[0]]
        for _ in range(2):
            await net.mine_on(miner_a, spacing_s=2.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "pre-partition mesh never converged"

        na = int(nodes * split)
        side_a, side_b = hosts[:na], hosts[na:]
        net.net.partition(side_a, side_b)
        miner_b = net.nodes[side_b[0]]
        for _ in range(blocks_major):
            await net.mine_on(miner_a, spacing_s=2.0)
        for _ in range(blocks_minor):
            await net.mine_on(miner_b, spacing_s=2.0)
        sides_ok = await net.run_until(
            lambda: net.converged(side_a) and net.converged(side_b),
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        diverged = len(net.tips()) == 2

        heal_at = net.clock.now
        net.net.heal()
        # One fresh block on the majority side: the announcement that
        # races the heal (nodes with cross links hear it immediately;
        # everyone else must hear it through the post-sync tip
        # announce).
        await net.mine_on(miner_a, spacing_s=2.0)
        final_height = 2 + blocks_major + 1
        healed = await net.run_until(
            lambda: net.converged() and min(net.heights()) == final_height,
            heal_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        heal_vs = net.clock.now - heal_at
        minority_reorged = sum(
            1 for h in side_b if net.nodes[h].metrics.reorgs > 0
        )
        report = _report(
            net, "partition-heal", t0,
            split=[len(side_a), len(side_b)],
            sides_converged_under_partition=sides_ok,
            tips_diverged=diverged,
            healed=healed,
            heal_virtual_s=round(heal_vs, 3),
            final_height=final_height,
            minority_nodes_reorged=minority_reorged,
        )
        report["ok"] = bool(
            healed
            and diverged
            and sides_ok
            and report["converged"]
            and report["ledger_conserved"]
            # The minority side really did live on its own chain and
            # really was reorged back — blocks_minor > 0 makes this a
            # structural requirement, not a vacuous pass.
            and (blocks_minor == 0 or minority_reorged >= 0.9 * len(side_b))
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- flash-crowd IBD -----------------------------------------------------


def flash_crowd(
    joiners: int = 500,
    chain_height: int = 20,
    seed: int = 0,
    difficulty: int = 8,
    join_window_vs: float = 5.0,
    ibd_timeout_vs: float = 300.0,
    wall_limit_s: float | None = 420.0,
) -> dict:
    """``joiners`` fresh nodes storm one seed node inside
    ``join_window_vs`` virtual seconds.  The seed's MAX_PEERS /
    MAX_HANDSHAKING caps refuse most of the herd — each joiner also
    knows one random earlier joiner, and the crowd must sync through
    itself.  ok = every node at the seed's tip within the budget."""
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0xF1A5)
        seed_node = await net.add_node()
        seed_host = net.host_name(0)
        for _ in range(chain_height):
            await net.mine_on(seed_node, spacing_s=0.05)
        assert seed_node.chain.height == chain_height

        stagger = join_window_vs / max(1, joiners)
        for i in range(1, joiners + 1):
            peers = [seed_host]
            if i > 1:
                peers.append(net.host_name(rng.randrange(1, i)))
            await net.add_node(peers=peers)
            await asyncio.sleep(stagger)
        join_done = net.clock.now

        done = await net.run_until(
            lambda: min(net.heights()) == chain_height and net.converged(),
            ibd_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        ibd_vs = net.clock.now - join_done
        seed_peers = seed_node.peer_count()
        report = _report(
            net, "flash-crowd", t0,
            joiners=joiners,
            chain_height=chain_height,
            ibd_complete=done,
            ibd_virtual_s=round(ibd_vs, 3),
            seed_peer_count=seed_peers,
            # The crowd was bigger than the seed's open-arms policy:
            # the interesting regime is the refused majority syncing
            # through the mesh, and this records that it happened.
            seed_capped=seed_peers < joiners,
        )
        report["ok"] = bool(
            done and report["converged"] and report["ledger_conserved"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- churn storm ---------------------------------------------------------


def churn_storm(
    nodes: int = 60,
    cycles: int = 5,
    churn_frac: float = 0.25,
    seed: int = 0,
    degree: int = 4,
    difficulty: int = 8,
    settle_timeout_vs: float = 120.0,
    wall_limit_s: float | None = 300.0,
) -> dict:
    """Waves of nodes vanish mid-gossip and return (same identity, same
    address — a restart, not a new peer) while the survivors keep
    mining.  ok = after the storm, every node — returners included —
    converges on one tip and conserves the ledger."""
    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()

    async def main():
        rng = random.Random(seed ^ 0xC4B1)
        for i in range(nodes):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, degree)]
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == 2,
            60, step=0.1, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-churn"

        restarts = 0
        for _cycle in range(cycles):
            victims = rng.sample(hosts[1:], int((nodes - 1) * churn_frac))
            for h in victims:
                await net.stop_node(h)
            # Mine while they are gone: the returners restart behind
            # the tip and must catch up through ordinary sync.
            await net.mine_on(miner, spacing_s=1.0)
            await asyncio.sleep(2.0)
            for h in victims:
                await net.restart_node(h)
                restarts += 1
            await net.mine_on(miner, spacing_s=1.0)
            await asyncio.sleep(2.0)

        final_height = 2 + 2 * cycles
        settled = await net.run_until(
            lambda: net.converged() and min(net.heights()) == final_height,
            settle_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        report = _report(
            net, "churn", t0,
            cycles=cycles,
            restarts=restarts,
            settled=settled,
            final_height=final_height,
        )
        report["ok"] = bool(
            settled and report["converged"] and report["ledger_conserved"]
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- eclipse attempt -----------------------------------------------------


def eclipse(
    honest: int = 24,
    attackers: int = 8,
    spam_rounds: int = 30,
    seed: int = 0,
    difficulty: int = 8,
    wall_limit_s: float | None = 240.0,
) -> dict:
    """Attackers flood a victim's address book from ``attackers``
    distinct hosts — hundreds of addresses pointing into attacker
    space — and run hostile listeners the victim's discovery may dial.
    The round-4 eclipse defenses under test: gossip can only churn the
    "new" bucket (handshake-verified "tried" entries are out of reach),
    per-HOST token buckets clamp unsolicited ADDR no matter how many
    frames arrive, and the book stays bounded.  ok = the victim keeps
    ≥1 honest connection, keeps converging with the honest mesh, and
    attacker addresses never exceed the budgeted trickle."""
    from p1_tpu.node import protocol
    from p1_tpu.node.node import MAX_KNOWN_ADDRS, MAX_TRIED_ADDRS
    from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    ATTACKER_NET = "66.6."

    async def main():
        rng = random.Random(seed ^ 0xEC11)
        for i in range(honest):
            await net.add_node(
                peers=[net.host_name(j) for j in _topology_peers(rng, i, 3)]
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        # The victim: discovery ON — exactly the machinery an eclipse
        # targets (it dials what the book tells it to).
        victim_host = "10.9.9.9"
        victim = await net.add_node(
            name=victim_host, peers=[hosts[0]], target_peers=4
        )
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and victim.chain.height == 2,
            60, step=0.1, wall_limit_s=wall_limit_s,
        ), "victim never joined the honest mesh"

        # Hostile listeners the poisoned book would dial into: they
        # answer the handshake (advertising height 0 — nothing to
        # serve) and otherwise waste the victim's time.
        listeners = []
        chain = make_blocks(1, difficulty)  # genesis only: right chain id
        for a in range(attackers):
            hp = HostilePeer(
                chain,
                plan=FaultPlan(hello_height=0),
                transport=net.net.host(f"{ATTACKER_NET}0.{a}"),
                host=f"{ATTACKER_NET}0.{a}",
                rng=random.Random(seed * 1000 + a),
            )
            await hp.start()
            listeners.append(hp)

        async def spam(a: int) -> None:
            """One attacker host streams ADDR frames at the victim:
            64 addresses per frame, every frame pointing into attacker
            space (the listeners above plus void)."""
            srng = random.Random(seed * 77 + a)
            src = f"{ATTACKER_NET}0.{a}"
            try:
                reader, writer = await net.net.host(src).connect(
                    victim_host, NODE_PORT
                )
                await protocol.write_frame(
                    writer,
                    protocol.encode_hello(
                        protocol.Hello(
                            miner.chain.genesis.block_hash(),
                            0,
                            listeners[a].port,
                            srng.getrandbits(64) | 1,
                        )
                    ),
                )
                await protocol.read_frame(reader)  # victim's HELLO
                for _ in range(spam_rounds):
                    addrs = [
                        (
                            f"{ATTACKER_NET}{srng.randrange(1, 250)}."
                            f"{srng.randrange(250)}",
                            srng.randrange(1, 0xFFFF),
                        )
                        for _ in range(64)
                    ]
                    await protocol.write_frame(
                        writer, protocol.encode_addr(addrs)
                    )
                    await asyncio.sleep(0.2)
                writer.close()
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                pass  # victim dropped us: also an answer

        await asyncio.gather(*(spam(a) for a in range(attackers)))
        await asyncio.sleep(5.0)

        # Post-storm: the honest mesh keeps mining; the victim must
        # still follow it.
        for _ in range(2):
            await net.mine_on(miner, spacing_s=1.0)
        followed = await net.run_until(
            lambda: victim.chain.tip_hash == miner.chain.tip_hash,
            60, step=0.1, wall_limit_s=wall_limit_s,
        )

        honest_set = set(hosts)
        honest_links = sum(
            1
            for p in victim._peers.values()
            if p.host in honest_set
        )
        tried_attacker = sum(
            1
            for (h, _pt) in victim._tried_addrs
            if h.startswith(ATTACKER_NET)
        )
        known_attacker = sum(
            1
            for (h, _pt) in victim._known_addrs
            if h.startswith(ATTACKER_NET)
        )
        book = len(victim._known_addrs) + len(victim._tried_addrs)
        spam_sent = attackers * spam_rounds * 64
        report = _report(
            net, "eclipse", t0,
            attackers=attackers,
            spam_addrs_sent=spam_sent,
            victim_honest_links=honest_links,
            victim_followed_honest_tip=followed,
            tried_bucket_attacker_entries=tried_attacker,
            new_bucket_attacker_entries=known_attacker,
            address_book_size=book,
            address_book_bounded=book
            <= MAX_KNOWN_ADDRS + MAX_TRIED_ADDRS,
        )
        # The ADDR budget admits ~1 address/host/second plus the burst:
        # anything near the spam volume means the bucket failed.
        budget_held = known_attacker <= attackers * 80
        report["ok"] = bool(
            followed
            and honest_links >= 1
            and tried_attacker == 0
            and budget_held
            and report["address_book_bounded"]
            and report["ledger_conserved"]
        )
        for hp in listeners:
            await hp.stop()
        await net.stop_all()
        return report

    return net.run(main())


# -- WAN topology --------------------------------------------------------

#: One-way inter-region latencies (seconds) for the wan scenario —
#: deliberately asymmetric (routing asymmetry is real) so the model is
#: exercised per DIRECTION.
_WAN_LATENCY = {
    ("us", "eu"): 0.040,
    ("eu", "us"): 0.048,
    ("us", "asia"): 0.080,
    ("asia", "us"): 0.092,
    ("eu", "asia"): 0.120,
    ("asia", "eu"): 0.132,
    ("us", "au"): 0.095,
    ("au", "us"): 0.110,
    ("eu", "au"): 0.140,
    ("au", "eu"): 0.155,
    ("asia", "au"): 0.060,
    ("au", "asia"): 0.070,
}


def wan(
    region_nodes: int = 10,
    blocks: int = 6,
    seed: int = 0,
    difficulty: int = 8,
    inter_bandwidth_bps: float = 100e6,
    wall_limit_s: float | None = 240.0,
    telemetry: bool = True,
    propagation_p95_bound_ms: float = 1500.0,
) -> dict:
    """Four regions (us/eu/asia/au) with asymmetric inter-region
    latency and shaped bandwidth; blocks are mined round-robin across
    regions.  ok = global convergence, the measured propagation p95
    actually shows the geography (at least one inter-region one-way
    latency — the proof the latency model is load-bearing), AND — from
    the round-14 telemetry histograms — the mesh-wide virtual-time
    propagation p95 stays under ``propagation_p95_bound_ms``: a few
    gossip hops across the worst configured path, an actual latency SLO
    instead of bare convergence."""
    regions = ("us", "eu", "asia", "au")
    net = SimNet(
        seed=seed,
        difficulty=difficulty,
        default_profile=LinkProfile(latency_s=0.002, jitter_s=0.001),
        telemetry=telemetry,
    )
    t0 = time.monotonic()

    def region_host(r: str, i: int) -> str:
        return f"10.{regions.index(r) + 1}.0.{i}"

    async def main():
        rng = random.Random(seed ^ 0x3A11)
        by_region: dict[str, list[str]] = {r: [] for r in regions}
        # Profiles first (between region /24s), then nodes: every pair
        # of cross-region hosts gets the matrix latency + shared
        # bandwidth shaping; intra-region stays on the LAN default.
        all_hosts = [
            (r, region_host(r, i))
            for r in regions
            for i in range(region_nodes)
        ]
        for ra, ha in all_hosts:
            for rb, hb in all_hosts:
                if ra != rb:
                    net.net.set_profile(
                        ha,
                        hb,
                        LinkProfile(
                            latency_s=_WAN_LATENCY[(ra, rb)],
                            jitter_s=0.004,
                            bandwidth_bps=inter_bandwidth_bps,
                        ),
                        symmetric=False,
                    )
        for idx, (r, host) in enumerate(all_hosts):
            peers = []
            mine_region = by_region[r]
            if mine_region:
                peers.append(mine_region[-1])  # region backbone
                if len(mine_region) > 1:
                    peers.append(mine_region[rng.randrange(len(mine_region))])
            if idx > 0 and (not mine_region or len(mine_region) % 3 == 1):
                # A gateway link into the regions dialed so far.
                others = [h for _r, h in all_hosts[:idx] if _r != r]
                if others:
                    peers.append(others[rng.randrange(len(others))])
            await net.add_node(name=host, peers=peers)
            by_region[r].append(host)
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "wan mesh never formed"

        for b in range(blocks):
            r = regions[b % len(regions)]
            await net.mine_on(
                net.nodes[by_region[r][0]], spacing_s=3.0
            )
        done = await net.run_until(
            lambda: net.converged() and min(net.heights()) == blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        )
        summaries = [
            n.metrics.propagation_summary() for n in net.nodes.values()
        ]
        p95s = [s["p95_ms"] for s in summaries if s["p95_ms"] is not None]
        max_p95_ms = max(p95s) if p95s else 0.0
        min_inter_ms = 1e3 * min(_WAN_LATENCY.values())
        report = _report(
            net, "wan", t0,
            regions={r: len(by_region[r]) for r in regions},
            blocks=blocks,
            propagation_max_p95_ms=max_p95_ms,
            min_inter_region_latency_ms=min_inter_ms,
            geography_visible=max_p95_ms >= min_inter_ms,
        )
        # The telemetry-histogram SLO: mesh-wide p95 propagation (in
        # virtual ms, merged across every node) under the bound.  With
        # telemetry disabled there is no histogram to assert on — the
        # SLO is vacuously out of scope and `ok` falls back to the
        # pre-round-14 criteria.
        prop = report["telemetry"]["propagation"]
        report["propagation_p95_bound_ms"] = propagation_p95_bound_ms
        report["propagation_bounded"] = (
            prop is None or prop["p95_ms"] <= propagation_p95_bound_ms
        )
        report["ok"] = bool(
            done
            and report["converged"]
            and report["ledger_conserved"]
            and report["geography_visible"]
            and report["propagation_bounded"]
            and (not telemetry or prop is not None)
        )
        await net.stop_all()
        return report

    return net.run(main())


# -- snapshot join (untrusted snapshot sync) -----------------------------


def snapshot_join(
    nodes: int = 16,
    chain_blocks: int = 10,
    seed: int = 0,
    difficulty: int = 8,
    interval: int = 4,
    lie: str | None = None,
    liar_height: int = 12,
    verdict_timeout_vs: float = 300.0,
    wall_limit_s: float | None = 240.0,
) -> dict:
    """Untrusted snapshot sync (chain/snapshot.py) at mesh scale.

    Honest form (``lie=None``): a fresh node joins a converged mesh
    with ``--snapshot-sync`` on, boots ASSUMED from a peer-served
    checkpoint snapshot, serves balance queries immediately, and must
    flip to fully-validated once the background replay reproduces the
    state root.  The report measures the assumed-boot and flip times in
    virtual seconds, and re-checks every balance the joiner reported
    while ASSUMED against the audit view of the validated chain — the
    never-contradicted invariant.

    Lying form (``lie`` in "balance"/"root"/"truncate"/"stall"): the
    joiner's FIRST peer is a hostile snapshot server running that
    pathology on a taller fork.  ok = the joiner detects/contains it
    (divergence + quarantine for the internally-consistent "balance"
    lie; refusal/failover for the rest), ends fully-validated, and the
    whole network still converges with the ledger conserved."""
    from p1_tpu.chain.ledger import balances as audit_balances
    from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

    net = SimNet(seed=seed, difficulty=difficulty)
    t0 = time.monotonic()
    WALLET = "snapshot-wallet"

    async def main():
        rng = random.Random(seed ^ 0x54A9)
        for i in range(nodes):
            await net.add_node(
                peers=[
                    net.host_name(j) for j in _topology_peers(rng, i, 3)
                ],
                snapshot_interval=interval,
                **({"miner_id": WALLET} if i == 0 else {}),
            )
        hosts = list(net.nodes)
        miner = net.nodes[hosts[0]]
        assert await net.run_until(
            net.links_up, 60, step=0.1, wall_limit_s=wall_limit_s
        ), "mesh never formed"
        for _ in range(chain_blocks):
            await net.mine_on(miner, spacing_s=1.0)
        assert await net.run_until(
            lambda: net.converged() and min(net.heights()) == chain_blocks,
            120, step=0.25, wall_limit_s=wall_limit_s,
        ), "mesh never converged pre-join"

        peers = [hosts[0], hosts[1]]
        liar = None
        if lie is not None:
            from p1_tpu.node.protocol import MsgType

            if lie in ("balance", "root"):
                plan = FaultPlan(snapshot_lie=lie)
            elif lie == "truncate":
                plan = FaultPlan(snapshot_chunks=1)
            else:
                plan = FaultPlan(swallow=frozenset({MsgType.GETSNAPSHOT}))
            src = "66.9.9.1"
            liar = HostilePeer(
                make_blocks(liar_height, difficulty, miner_id="snapliar"),
                plan=plan,
                transport=net.net.host(src),
                host=src,
                rng=random.Random(seed * 31 + 7),
            )
            await liar.start()
            peers = [f"{src}:{liar.port}", hosts[0]]

        join_at = net.clock.now
        joiner = await net.add_node(
            name="10.99.9.9",
            peers=peers,
            snapshot_sync=True,
            snapshot_interval=interval,
            snapshot_min_lead=2,
        )
        assumed = await net.run_until(
            lambda: joiner.validation_state == "assumed",
            60, step=0.1, wall_limit_s=wall_limit_s,
        )
        assumed_vs = net.clock.now - join_at
        samples: list[tuple[int, bytes, int]] = []

        def sample():
            if joiner.validation_state == "assumed":
                samples.append(
                    (
                        joiner.chain.height,
                        joiner.chain.tip_hash,
                        joiner.chain.balance(WALLET),
                    )
                )
            return False

        await net.run_until(
            sample, 2.0, step=0.5, wall_limit_s=wall_limit_s
        )
        verdict = await net.run_until(
            lambda: joiner.validation_state == "validated"
            and joiner._bg_chain is None,
            verdict_timeout_vs, step=0.25, wall_limit_s=wall_limit_s,
        )
        verdict_vs = net.clock.now - join_at
        # Post-verdict: one more honest block must reach the joiner.
        await net.mine_on(miner, spacing_s=1.0)
        settled = await net.run_until(
            lambda: net.converged(), 120, step=0.25,
            wall_limit_s=wall_limit_s,
        )
        contradicted = 0
        ref = net.nodes[hosts[0]].chain
        if joiner.metrics.snapshot_flips:
            for height, tip_hash, reported in samples:
                if ref.main_hash_at(height) != tip_hash:
                    continue  # claim's block reorged away: retracted
                blocks = [
                    ref._block_at(ref.main_hash_at(h))
                    for h in range(height + 1)
                ]
                if audit_balances(blocks).get(WALLET, 0) != reported:
                    contradicted += 1
        report = _report(
            net, "snapshot-join", t0,
            lie=lie,
            assumed=assumed,
            assumed_virtual_s=round(assumed_vs, 3),
            verdict=verdict,
            verdict_virtual_s=round(verdict_vs, 3),
            flips=joiner.metrics.snapshot_flips,
            divergences=joiner.metrics.snapshot_divergences,
            assumed_samples=len(samples),
            samples_contradicted=contradicted,
        )
        if lie is None:
            report["ok"] = bool(
                assumed
                and verdict
                and settled
                and joiner.metrics.snapshot_flips == 1
                and joiner.metrics.snapshot_divergences == 0
                and contradicted == 0
                and report["ledger_conserved"]
            )
        elif lie == "balance":
            # Internally consistent lie: adopted, then CAUGHT by the
            # background replay — quarantined, fallen back, converged.
            report["ok"] = bool(
                assumed
                and verdict
                and settled
                and joiner.metrics.snapshot_divergences >= 1
                and joiner.metrics.snapshot_flips == 0
                and report["ledger_conserved"]
            )
        else:
            # root/truncate/stall: refused or failed over BEFORE any
            # state was trusted — the joiner may end up assuming an
            # honest peer's snapshot instead (and must then flip).
            report["ok"] = bool(
                verdict
                and settled
                and contradicted == 0
                and report["ledger_conserved"]
            )
        if liar is not None:
            await liar.stop()
        await net.stop_all()
        return report

    return net.run(main())


# -- registry / CLI entry ------------------------------------------------

SCENARIOS = {
    "partition-heal": partition_heal,
    "flash-crowd": flash_crowd,
    "churn": churn_storm,
    "eclipse": eclipse,
    "wan": wan,
    "snapshot-join": snapshot_join,
}


def run_scenario(name: str, **kwargs) -> dict:
    """Run one named scenario; unknown kwargs raise TypeError (the CLI
    filters per-scenario flags before calling)."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have: {', '.join(sorted(SCENARIOS))}"
        ) from None
    return fn(**kwargs)
