"""Byzantine actor suite: actively malicious net-soak participants.

Extracted from ``cli.py`` (which keeps only parsing + dispatch): the
whole hostile repertoire one `p1 net --byzantine N` actor cycles —
invalid signatures, overdraws, replays of confirmed transfers, forged
compact-block material, unsolicited BLOCKTXN, ADDR spam, oversized
frames, random garbage, and the silent camping session the liveness
layer exists to reap.  Test/soak infrastructure, not product: nothing in
the node imports this.  It lives in the package (like ``testing.py``'s
HostilePeer/GreedyPeer) so external rigs can drive the same adversaries
against real nodes without vendoring CLI internals.
"""

from __future__ import annotations

import asyncio

from p1_tpu.node.transport import SOCKET_TRANSPORT


def new_stats() -> dict:
    """The shared mutable stats dict every actor feeds (one per soak)."""
    return {
        "attacks": {},
        "refused_connects": 0,
        "slow_hellos": 0,
        "camp_evictions": 0,
    }


async def sketch_poisoner(
    host, port, difficulty, deadline, retarget, stats: dict,
    transport=None,
) -> None:
    """A recon-plane adversary (round 23): a LISTENING peer that
    completes an honest handshake with a real node nonce — so victims
    that dial it treat the link as reconciliation-capable — then poisons
    every reconciliation primitive it touches:

    - answers each REQRECON with a garbage sketch (random bytes of a
      plausible length), so the victim's decode fails every round;
    - initiates its own REQRECON spam, burning responder sketch serves;
    - closes the victim's sketches with RECONCILDIFF frames full of
      fabricated short ids the victim will chase (bounded by its GETTX
      one-shot) and sprays GETTX for ids nothing maps to (bounded by
      the responder's pool-scan cap).

    A separate actor from ``byzantine_actor`` ON PURPOSE: that actor's
    seeded ``rng.choice`` attack schedule is pinned by existing scenario
    traces, and extending its tuple would silently re-roll every one.

    The honest invariant it exists to prove (asserted by the scenario,
    not here): relay cannot be stalled — the victim burns a few failed
    rounds, demotes the link to plain flood (``recon_demotions``), and
    every honest transaction still propagates mesh-wide.  Runs until
    ``deadline`` on the transport's wall clock."""
    import random

    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.node import protocol
    from p1_tpu.node.protocol import Hello, MsgType

    transport = transport if transport is not None else SOCKET_TRANSPORT
    clock = transport.clock
    rng = random.Random(0x5EED ^ port)
    gh = make_genesis(difficulty, retarget).block_hash()
    nonce = rng.getrandbits(64) | 1  # a "real node", per the handshake

    def bump(name: str) -> None:
        stats["attacks"][name] = stats["attacks"].get(name, 0) + 1

    async def session(reader, writer) -> None:
        try:
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(gh, 0, port, nonce))
            )
            await asyncio.wait_for(protocol.read_frame(reader), 10)
            last_spam = clock.wall()
            while clock.wall() < deadline:
                payload = await asyncio.wait_for(
                    protocol.read_frame(reader),
                    timeout=max(0.1, deadline - clock.wall()),
                )
                if not payload:
                    continue
                if payload[0] == MsgType.REQRECON:
                    # A garbage sketch of a believable size: syndrome
                    # words drawn uniformly decode to None with
                    # overwhelming probability — every round the victim
                    # initiates on this link fails.
                    words = rng.randrange(2, 34)
                    await protocol.write_frame(
                        writer,
                        protocol.encode_sketch(
                            rng.randrange(1, 512),
                            rng.randbytes(4 * words),
                        ),
                    )
                    bump("garbage_sketch")
                elif payload[0] == MsgType.SKETCH:
                    # Our own spam round came back: claim success with
                    # fabricated "theirs" ids the victim will chase.
                    await protocol.write_frame(
                        writer,
                        protocol.encode_recondiff(
                            True,
                            tuple(
                                rng.randrange(1, 1 << 32) for _ in range(32)
                            ),
                        ),
                    )
                    bump("fake_diff")
                if clock.wall() - last_spam >= 0.5:
                    last_spam = clock.wall()
                    await protocol.write_frame(
                        writer,
                        protocol.encode_reqrecon(rng.randrange(0, 4096)),
                    )
                    bump("reqrecon_spam")
                    await protocol.write_frame(
                        writer,
                        protocol.encode_gettx(
                            tuple(
                                rng.randrange(1, 1 << 32) for _ in range(64)
                            )
                        ),
                    )
                    bump("gettx_spray")
        except (
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            ValueError,
        ):
            pass  # victim hung up or the clock ran out: session over
        finally:
            writer.close()

    # The session coroutine doubles as the accept callback: both the
    # socket transport (asyncio.start_server) and the simulator wrap it
    # in a task per inbound connection.
    listener = await transport.listen(session, host, port)
    try:
        while clock.wall() < deadline:
            await asyncio.sleep(0.25)
    finally:
        listener.close()


async def byzantine_actor(
    actor: int, ports, difficulty, deadline, retarget, stats: dict,
    transport=None,
) -> None:
    """One actively malicious participant (VERDICT r4 weak #5): connects
    to honest nodes from its own loopback alias (127.0.0.{10+actor}, so
    misbehavior bans hit the attacker's address, not the honest mesh's)
    and cycles the whole hostile repertoire.  Counts what it sent and how
    often the node refused it at accept time (= an active ban).  Every
    attack is fire-and-observe: the honest invariants are asserted from
    the nodes' final statuses, not from here.

    ``ports`` entries are localhost port numbers (the historical `p1
    net` shape) or explicit ``(host, port)`` targets; ``transport``
    (node/transport.py) defaults to real sockets — a netsim facade runs
    the identical repertoire, clock included, against a simulated mesh
    (the scenario corpus's containment runs).  ``deadline`` is read
    against the transport's wall clock either way."""
    import dataclasses
    import random
    import struct

    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node import protocol
    from p1_tpu.node.protocol import Hello, MsgType

    transport = transport if transport is not None else SOCKET_TRANSPORT
    clock = transport.clock
    targets = [
        ("127.0.0.1", p) if isinstance(p, int) else (p[0], int(p[1]))
        for p in ports
    ]
    rng = random.Random(0xBAD + actor)
    source = f"127.0.0.{10 + actor}"
    genesis = make_genesis(difficulty, retarget)
    gh = genesis.block_hash()
    tag = gh
    key = Keypair.from_seed_text(f"p1-byz-{actor}")
    harvested_txs: list[bytes] = []  # raw TX payloads seen in gossip
    harvested_headers: list[BlockHeader] = []

    def bump(name: str) -> None:
        stats["attacks"][name] = stats["attacks"].get(name, 0) + 1

    while clock.wall() < deadline - 1.0:
        host, port = targets[rng.randrange(len(targets))]
        try:
            reader, writer = await transport.connect(
                host, port, local_addr=(source, 0)
            )
        except OSError:
            await asyncio.sleep(0.2)
            continue
        try:
            first = await asyncio.wait_for(protocol.read_frame(reader), 5)
            mtype, _ = protocol.decode(first)
            assert mtype is MsgType.HELLO
        except asyncio.TimeoutError:
            # Slow HELLO ≠ ban: a GIL-loaded honest node can take
            # seconds — counting it as a refusal would let bans_fired
            # read true with the ban machinery broken.
            stats["slow_hellos"] = stats.get("slow_hellos", 0) + 1
            writer.close()
            await asyncio.sleep(0.2)
            continue
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            ValueError,
        ):
            # Immediate hang-up before HELLO: the accept-time ban said no.
            stats["refused_connects"] += 1
            writer.close()
            await asyncio.sleep(0.2)
            continue
        harvester = None
        try:
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(gh, 0, 0, 0))
            )
            session_end = min(deadline - 0.5, clock.wall() + 2.0)

            async def harvest() -> None:
                try:
                    while True:
                        payload = await protocol.read_frame(reader)
                        if not payload:
                            continue
                        if (
                            payload[0] == MsgType.TX
                            and len(harvested_txs) < 64
                        ):
                            harvested_txs.append(payload)
                        elif payload[0] == MsgType.BLOCK:
                            try:
                                _, (_ts, blk) = protocol.decode(payload)
                                if len(harvested_headers) < 16:
                                    harvested_headers.append(blk.header)
                            except ValueError:
                                pass
                except (
                    asyncio.IncompleteReadError,
                    ConnectionError,
                    OSError,
                ):
                    return  # node hung up on us (a ban working) — done

            harvester = asyncio.create_task(harvest())
            if deadline - clock.wall() >= 25.0 and rng.random() < 0.25:
                # A CAMPING session — the round-4 verdict's exact
                # slot-pinning profile: hold the connection, reading but
                # never sending, until the liveness layer reaps us.
                # Decided ONCE per session with small probability (a
                # per-iteration draw converted ~99% of sessions into
                # camps and starved the ban machinery the containment
                # contract asserts), and skipped near the deadline so
                # short runs still exercise every other attack.  The
                # session sends nothing after HELLO, so a teardown here
                # is attributable to the keepalive probe (accept-time
                # bans close pre-HELLO and never reach this point).
                bump("camp")
                camp_end = clock.wall() + 20.0
                while clock.wall() < camp_end:
                    if writer.is_closing() or harvester.done():
                        stats["camp_evictions"] += 1
                        break
                    await asyncio.sleep(0.5)
            else:
                while clock.wall() < session_end:
                    attack = rng.choice(
                        (
                            "badsig",
                            "overdraw",
                            "replay",
                            "cblock",
                            "blocktxn",
                            "addr_spam",
                            "garbage",
                        )
                    )
                    if attack == "replay" and not harvested_txs:
                        attack = "garbage"  # nothing harvested yet
                    if attack == "cblock" and not harvested_headers:
                        attack = "garbage"
                    if attack == "badsig":
                        tx = Transaction.transfer(
                            key, "p1deadbeefdeadbeef", 1, 1, 0, chain=tag
                        )
                        forged = dataclasses.replace(
                            tx, sig=bytes(64)  # zeroed signature
                        )
                        await protocol.write_frame(
                            writer, protocol.encode_tx(forged)
                        )
                    elif attack == "overdraw":
                        tx = Transaction.transfer(
                            key,
                            "p1deadbeefdeadbeef",
                            10**12,  # the attacker's balance is zero
                            1,
                            0,
                            chain=tag,
                        )
                        await protocol.write_frame(writer, protocol.encode_tx(tx))
                    elif attack == "replay":
                        # A transfer harvested from gossip earlier: by now
                        # confirmed on-chain — a definite nonce replay.
                        await protocol.write_frame(
                            writer, harvested_txs[rng.randrange(len(harvested_txs))]
                        )
                    elif attack == "cblock":
                        # Real recent header with the nonce bumped: parent
                        # known, PoW broken — must die at the work gate.
                        h = harvested_headers[-1]
                        fake = dataclasses.replace(h, nonce=h.nonce ^ 1)
                        payload = (
                            bytes([MsgType.CBLOCK])
                            + struct.pack(">d", clock.wall())
                            + fake.serialize()
                            + struct.pack(">HH", 1, 0)
                            + bytes(32)
                        )
                        await protocol.write_frame(writer, payload)
                    elif attack == "blocktxn":
                        await protocol.write_frame(
                            writer,
                            protocol.encode_blocktxn(
                                rng.randbytes(32), [rng.randbytes(40)]
                            ),
                        )
                    elif attack == "addr_spam":
                        addrs = [
                            (f"10.66.{rng.randrange(256)}.{rng.randrange(256)}",
                             rng.randrange(1, 0xFFFF))
                            for _ in range(64)
                        ]
                        await protocol.write_frame(
                            writer, protocol.encode_addr(addrs)
                        )
                    else:  # garbage: malformed bytes — a scorable violation
                        writer.write(
                            (rng.randrange(1, 64)).to_bytes(4, "big")
                            + rng.randbytes(rng.randrange(1, 64))
                        )
                        await writer.drain()
                    bump(attack)
                    await asyncio.sleep(0.05)
                # Sign off with the canonical scorable violation so bans
                # accumulate: a hostile length prefix.
                writer.write((64 << 20).to_bytes(4, "big"))
                await writer.drain()
                bump("oversized")
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass  # node dropped us mid-attack: working as intended
        finally:
            if harvester is not None:
                harvester.cancel()  # no-op if it already returned; its
                # own except clause swallows disconnects, so no
                # unretrieved-exception warnings either way
            writer.close()
        await asyncio.sleep(0.1)
