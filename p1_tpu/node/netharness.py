"""The `p1 net` soak harness: spawn a localhost mesh, drive it, audit it.

Extracted from ``cli.py`` (which keeps only parsing + dispatch): the
subprocess mesh spawner with its readiness handshake and shared mining
deadline, the benign signed-transfer economy (``inject_txs``), the
byzantine-actor co-driver (``node/byzantine.py``), and the summary
auditor — convergence, exact ledger conservation, byzantine containment,
memory bounds.  This is the repo's net-level soak rig; tests
(``tests/test_cli.py``) and operators invoke it through `p1 net`.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time

from p1_tpu.node.byzantine import byzantine_actor, new_stats


async def inject_txs(
    ports, keys, difficulty, deadline, rate, retarget=None
) -> tuple[int, int]:
    """Drive a live economy during a `p1 net` run: ~``rate`` transfers/sec,
    each one a real wallet round — GETACCOUNT for the sender's next seq at
    its own node, sign chain-bound, push via the tx client.  Best-effort:
    a busy node (GIL-bound mining) or an unaffordable pick just skips a
    beat; the audit invariant is conservation, not delivery."""
    import random

    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.client import get_account, send_tx

    tag = genesis_hash(difficulty, retarget)
    submitted = failed = 0
    rng = random.Random(0xD1CE)
    period = 1.0 / rate
    while time.time() < deadline - 1.0:
        i = rng.randrange(len(keys))
        recipient = keys[rng.randrange(len(keys))].account
        try:
            state = await get_account(
                "127.0.0.1",
                ports[i],
                keys[i].account,
                difficulty,
                timeout=5,
                retarget=retarget,
            )
            amount = rng.randint(1, 5)
            if state.balance >= amount + 1:
                tx = Transaction.transfer(
                    keys[i], recipient, amount, 1, state.next_seq, chain=tag
                )
                await send_tx(
                    "127.0.0.1",
                    ports[i],
                    tx,
                    difficulty,
                    timeout=5,
                    retarget=retarget,
                )
                submitted += 1
        except (
            ConnectionError,
            OSError,
            ValueError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
        ):
            failed += 1
        await asyncio.sleep(period)
    return submitted, failed


async def net_drive(
    ports, keys, difficulty, deadline, rate, n_byzantine, retarget=None
):
    """Run the benign economy and the byzantine actors concurrently."""
    byz_stats = new_stats()
    tasks = []
    if rate > 0:
        tasks.append(
            inject_txs(ports, keys, difficulty, deadline, rate, retarget)
        )
    for actor in range(n_byzantine):
        tasks.append(
            byzantine_actor(
                actor, ports, difficulty, deadline, retarget, byz_stats
            )
        )
    results = await asyncio.gather(*tasks, return_exceptions=True)
    submitted = failed = 0
    for r in results:
        if isinstance(r, tuple):
            submitted, failed = r
        elif isinstance(r, BaseException):
            raise r
    return submitted, failed, byz_stats


def run_net(args) -> int:
    """Spawn N `p1_tpu node` subprocesses in a full mesh and check they
    converge on one tip (benchmark config 4, BASELINE.json:10).  With
    ``--tx-rate`` the run carries a live signed-transfer economy between
    the miners' accounts, and the summary audits every node's ledger for
    exact conservation — the whole consensus stack (signatures, nonces,
    overdraw rejection, reorg undo) exercised under real concurrent
    forks."""
    import subprocess

    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.retarget import RetargetRule

    # Validate the retarget flag pair up front: a bad pair must be ONE
    # clean CLI error here, not N child-node tracebacks (or — for a lone
    # --target-spacing — a silently fixed-difficulty run).
    try:
        net_rule = RetargetRule.from_params(
            getattr(args, "retarget_window", 0),
            getattr(args, "target_spacing", 0),
        )
    except ValueError as e:
        raise SystemExit(str(e))
    ports = [args.base_port + i for i in range(args.nodes)]
    keys = [
        Keypair.from_seed_text(f"p1-net-{args.base_port}-{i}")
        for i in range(args.nodes)
    ]
    procs = []
    for i, port in enumerate(ports):
        cmd = [
            sys.executable,
            "-m",
            "p1_tpu",
            "node",
            "--port",
            str(port),
            "--difficulty",
            str(args.difficulty),
            "--backend",
            args.backend,
            "--deadline",
            "stdin",
            "--miner-id",
            keys[i].account if args.tx_rate > 0 else f"node{i}",
        ]
        if args.chunk:
            cmd += ["--chunk", str(args.chunk)]
        if args.batch:
            cmd += ["--batch", str(args.batch)]
        # Tight liveness deadlines for the localhost mesh: a silent
        # camper (the byzantine "camp" attack, or any wedged peer) is
        # probed within 10 s and evicted 5 s later, so soak statuses
        # show the keepalive layer actually firing.  Honest miners
        # gossip constantly and never get probed.
        cmd += ["--ping-interval", "10", "--pong-timeout", "5"]
        # Tight sync supervision to match: a localhost batch turns
        # around in milliseconds, so a 5 s no-progress window on a
        # catch-up is decisively a stall — soak statuses surface the
        # failover layer under byzantine serve-and-starve peers while
        # honest syncs (progress resets the deadline) never trip it.
        cmd += ["--sync-stall-timeout", "5"]
        if net_rule is not None:
            cmd += [
                "--retarget-window", str(net_rule.window),
                "--target-spacing", str(net_rule.spacing),
            ]
        if args.no_compact_gossip:
            cmd += ["--no-compact-gossip"]
        if args.discover:
            # One seed only; discovery must assemble the mesh.
            peers = [f"127.0.0.1:{ports[0]}"] if i else []
            cmd += ["--target-peers", str(args.nodes - 1)]
        else:
            peers = [f"127.0.0.1:{p}" for p in ports[:i]]
        if peers:
            cmd += ["--peers", *peers]
        procs.append(
            subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True
            )
        )
    statuses = []
    try:
        # Readiness handshake: interpreter startup can cost many seconds on
        # a loaded host, so a deadline computed before the children exist
        # could expire before they boot.  Every child prints a ready line;
        # only then does the shared mining deadline start counting.
        for proc in procs:
            ready = json.loads(proc.stdout.readline())
            assert "ready" in ready, ready
        deadline = time.time() + args.duration
        for proc in procs:
            proc.stdin.write(f"{deadline!r}\n")
            proc.stdin.flush()  # leave stdin open: communicate() closes it
        txs_submitted = txs_failed = 0
        byz_stats = None
        n_byz = getattr(args, "byzantine", 0)
        if args.tx_rate > 0 or n_byz > 0:
            txs_submitted, txs_failed, byz_stats = asyncio.run(
                net_drive(
                    ports,
                    keys,
                    args.difficulty,
                    deadline,
                    args.tx_rate,
                    n_byz,
                    retarget=net_rule,
                )
            )
        for proc in procs:
            out, _ = proc.communicate(timeout=args.duration + 120)
            lines = (out or "").strip().splitlines()
            if not lines:
                raise RuntimeError(f"node pid {proc.pid} produced no status output")
            statuses.append(json.loads(lines[-1]))
    finally:
        for proc in procs:  # never leave orphaned miners holding the ports
            if proc.poll() is None:
                proc.kill()
    tips = {s["tip"] for s in statuses}
    result = {
        "config": "net",
        "nodes": args.nodes,
        "difficulty": args.difficulty,
        "converged": len(tips) == 1,
        "height": max(s["height"] for s in statuses),
        "blocks_mined_total": sum(s["blocks_mined"] for s in statuses),
        "reorgs_total": sum(s["reorgs"] for s in statuses),
        # Gossip bandwidth elided by compact block relay, net-wide.
        "compact_bytes_saved_total": sum(
            s["compact"]["bytes_saved"] for s in statuses
        ),
        "compact_tx_hit_total": sum(
            s["compact"]["tx_hits"] for s in statuses
        ),
        "compact_tx_fetched_total": sum(
            s["compact"]["tx_fetched"] for s in statuses
        ),
        "wire_bytes_total": sum(
            s["wire"]["bytes_sent"] for s in statuses
        ),
        # Network-level propagation delay (gossip send -> accept), the
        # worst node's view: median of per-node medians would hide a slow
        # peer, so report the max median and the max p95 across nodes.
        "propagation_delay_ms": {
            "max_median": max(
                (s["propagation"]["median_ms"] or 0.0 for s in statuses),
                default=0.0,
            ),
            "max_p95": max(
                (s["propagation"]["p95_ms"] or 0.0 for s in statuses),
                default=0.0,
            ),
            "samples_total": sum(s["propagation"]["samples"] for s in statuses),
        },
        "statuses": statuses,
    }
    if args.tx_rate > 0:
        from p1_tpu.core.tx import BLOCK_REWARD

        # Conservation: every block carries a coinbase and fees credit the
        # miner, so each node's ledger must sum to exactly reward x its
        # height — across hundreds of reorgs and a live spend stream.
        conserved = all(
            s["ledger_sum"] == BLOCK_REWARD * s["height"] for s in statuses
        )
        result["economy"] = {
            "txs_submitted": txs_submitted,
            "txs_failed": txs_failed,
            "txs_accepted_total": sum(s["txs_accepted"] for s in statuses),
            "ledger_conserved": conserved,
        }
        if not conserved:
            result["converged"] = False  # fail loudly: consensus bug
    if n_byz > 0 and byz_stats is not None:
        # The byzantine soak's containment contract, asserted in the
        # summary rather than left to log-reading: honest nodes must
        # have (a) kept converging and conserving (checked above),
        # (b) actually banned the attackers (their oversized/garbage
        # frames are scorable, so refused connects must appear), and
        # (c) stayed within their memory bounds — the address book and
        # pool caps hold under spam.
        from p1_tpu.mempool import Mempool
        from p1_tpu.node.node import MAX_KNOWN_ADDRS, MAX_TRIED_ADDRS

        attacks_sent = sum(byz_stats["attacks"].values())
        bans_fired = byz_stats["refused_connects"] > 0
        pool_cap = Mempool().max_txs  # the node's actual bound
        memory_bounded = all(
            s["known_addrs"] <= MAX_KNOWN_ADDRS + MAX_TRIED_ADDRS
            and s["mempool"] <= pool_cap
            for s in statuses
        )
        result["byzantine"] = {
            "attackers": n_byz,
            "attacks_sent": attacks_sent,
            "attacks": byz_stats["attacks"],
            "refused_connects": byz_stats["refused_connects"],
            "slow_hellos": byz_stats["slow_hellos"],
            # Silent-camper sessions the ATTACKERS saw torn down early
            # (camping sessions send nothing after HELLO, so these are
            # keepalive reaps), next to the nodes' aggregate idle-
            # eviction telemetry — an upper bound that can also include
            # an honest peer evicted during a GIL stall.
            "camp_evictions": byz_stats["camp_evictions"],
            "idle_evictions_total": sum(
                s.get("liveness", {}).get("peers_evicted_idle", 0)
                for s in statuses
            ),
            "bans_fired": bans_fired,
            "memory_bounded": memory_bounded,
            "contained": bool(
                result["converged"] and bans_fired and memory_bounded
            ),
        }
        if not result["byzantine"]["contained"]:
            result["converged"] = False
    print(json.dumps(result))
    return 0 if result["converged"] else 1
