"""Request supervision: progress deadlines for multi-round fetches.

The robustness gap this closes (VERDICT r5 Missing #2): every multi-round
fetch the node performs — locator block sync, the compact-block
GETBLOCKTXN round, paged mempool sync, the light client's headers loop —
was re-requested from the single peer that triggered it, forever.  The
liveness layer (node.py's probe/evict loop) only proves a peer is
*talking*; a peer that answers PINGs, or trickles bytes above the
MIN_FRAME_RATE floor, or serves syntactically valid replies that never
advance the chain, stays comfortably under that bar while pinning a fresh
node's catch-up indefinitely.  Bitcoin-family nodes carry a second,
sharper deadline for exactly this (the stalling-sync-peer timeout behind
headers-first IBD): *progress*, not liveness, is what buys a sync peer
its slot.

``RequestSupervisor`` is that deadline as a reusable state machine:

- one in-flight **target** (an opaque peer key) with a progress deadline
  — ``stalled()`` fires when the job has advanced nothing (blocks
  accepted, headers appended, pages consumed — the OWNER defines
  progress and calls ``progress()``) within ``stall_timeout_s``;
- a **jittered exponential backoff** between failovers (``record_stall``
  arms it, ``ready()`` gates the re-issue) so a mesh of recovering nodes
  doesn't re-ask in lockstep;
- a **bounded attempt budget**: ``attempts_max`` failovers per episode,
  reset whenever real progress lands (a live sync is not a failing one).

It is a pure state machine over an injectable clock and RNG (testable
without sleeping), and deliberately knows nothing about peers, sockets,
or messages: the owner decides who is eligible, performs the send, and —
critically — *demotes rather than bans* the staller.  Slowness is not a
protocol violation; the staller keeps its connection and merely loses
sync-peer priority (node.py's ``_Peer.sync_demerits``).
"""

from __future__ import annotations

import random
import secrets
import time

__all__ = ["RequestSupervisor", "SyncStalled"]

#: Default jitter band applied to every backoff delay: the computed delay
#: is scaled by a uniform draw from [0.5, 1.5).  Wide enough that two
#: nodes failing over off the same staller won't re-issue in lockstep.
_JITTER_LO = 0.5
_JITTER_SPAN = 1.0


class SyncStalled(ConnectionError):
    """A supervised fetch ran out of failover attempts: every eligible
    target stalled past its progress deadline.  A ``ConnectionError``
    subclass so existing callers that already handle dead-peer errors
    (CLI commands, retry loops) treat exhaustion the same way."""


class RequestSupervisor:
    """Progress-deadline bookkeeping for ONE multi-round fetch job.

    The owner drives it::

        sup.begin(peer)          # request sent; the deadline arms
        sup.progress()           # the job advanced; deadline + budget reset
        if sup.stalled():        # deadline expired with no progress
            delay = sup.record_stall()   # count it, arm jittered backoff
            ...pick a DIFFERENT target, wait sup.ready(), re-issue...
        sup.idle()               # job complete; nothing in flight

    All methods are synchronous and O(1); the owner polls from its own
    tick loop (node.py's ``_supervision_loop``) or wraps awaits in
    timeouts (client.py's headers fetch).
    """

    def __init__(
        self,
        *,
        stall_timeout_s: float,
        attempts_max: int,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 5.0,
        clock=time.monotonic,
        rng: random.Random | None = None,
    ):
        self.stall_timeout_s = float(stall_timeout_s)
        self.attempts_max = int(attempts_max)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        # The fallback seeds EXPLICITLY from OS entropy: production
        # jitter wants real randomness, but a bare random.Random() says
        # so only by omission — and the unseeded-rng lint rule can't
        # tell intent from a forgotten seed.  Simulated paths must pass
        # a seeded rng (the node wires config.rng_seed through here).
        self._rng = rng if rng is not None else random.Random(secrets.randbits(64))
        #: Opaque key of the peer the in-flight request targets (None =
        #: nothing supervised right now).
        self.target = None
        self._since: float | None = None
        self._retry_at = 0.0
        #: Failovers charged against the current episode (reset by
        #: progress — only *consecutive* stalls exhaust the budget).
        self.attempts = 0
        #: Lifetime stall count (telemetry; never reset).
        self.stalls = 0

    # -- owner signals ---------------------------------------------------

    def begin(self, target) -> None:
        """A request is now in flight against ``target``; arm the
        progress deadline.  Re-targeting an active job just moves the
        deadline — the job is one catch-up episode, not one request."""
        self.target = target
        self._since = self._clock()

    def progress(self) -> None:
        """The job advanced.  Resets the deadline AND the attempt budget:
        a sync that keeps landing blocks — however slowly — is healthy,
        and must never exhaust its budget by accumulating ancient
        stalls (the honest-slow-peer guarantee)."""
        if self.target is not None:
            self._since = self._clock()
        self.attempts = 0

    def idle(self) -> None:
        """The job completed (or its trigger evaporated): stop
        supervising until the next ``begin``."""
        self.target = None
        self._since = None

    # -- owner queries ---------------------------------------------------

    @property
    def active(self) -> bool:
        return self.target is not None

    def stalled(self) -> bool:
        """True when the in-flight request has outlived its progress
        deadline."""
        return (
            self._since is not None
            and self._clock() - self._since > self.stall_timeout_s
        )

    def exhausted(self) -> bool:
        """True when the episode's failover budget is spent."""
        return self.attempts >= self.attempts_max

    def ready(self) -> bool:
        """True when the backoff armed by the last ``record_stall`` has
        elapsed — the gate on re-issuing the request."""
        return self._clock() >= self._retry_at

    def record_stall(self) -> float:
        """Count one stall: charge an attempt, clear the in-flight
        target, and arm a jittered exponential backoff.  Returns the
        delay until ``ready()`` — callers that sleep (the headers client)
        use it directly; pollers (the node loop) just re-check."""
        self.stalls += 1
        self.attempts += 1
        # Exponent clamped: a caller that disables exhaustion (the store
        # recovery loop runs with attempts_max effectively infinite) can
        # accumulate thousands of attempts, and 2**attempts would
        # overflow the int->float conversion long before the min() could
        # discard it.  Past the clamp the delay is backoff_max_s anyway.
        delay = min(
            self.backoff_max_s,
            self.backoff_base_s * (2.0 ** min(self.attempts - 1, 60)),
        )
        delay *= _JITTER_LO + _JITTER_SPAN * self._rng.random()
        self._retry_at = self._clock() + delay
        self.idle()
        return delay
