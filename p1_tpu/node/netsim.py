"""Deterministic discrete-event network simulator: 1000 nodes, one host.

The scale wall (ROADMAP item 4): the repo's harnesses drive real sockets
and real clocks, which tops out around seven heavily-loaded nodes on the
1-vCPU host — and couples every liveness/stall deadline to scheduler
noise, the root cause behind every wall-clock deflake of rounds 6–9.
Bitcoin-Core-lineage systems validate emergent consensus behavior
(partition heal, eclipse resistance, churn, flash-crowd IBD) on
*simulated* thousand-node meshes.  This module is that substrate,
layered on the transport seam (node/transport.py):

- ``VirtualClock`` — a number.  Nothing sleeps; time IS the event queue.
- ``SimLoop`` — an ordinary asyncio selector loop whose ``time()`` is
  the virtual clock and whose idle step JUMPS the clock to the next
  scheduled timer instead of blocking.  Every ``asyncio.sleep`` /
  ``wait_for`` inside every node is thereby virtualized with zero code
  changes: a 60 s keepalive interval costs microseconds of wall time,
  and a mesh that would need 20 real minutes of gossip settles in
  seconds.
- ``SimTransport`` — the in-memory network.  One ``host(name)`` facade
  per participant (so per-host accounting — bans, ADDR budgets — keeps
  working); per-link ``LinkProfile`` with latency, jitter, bandwidth
  shaping, and loss; FIFO per-link delivery; partitions that sever live
  connections and refuse new ones until ``heal()``.
- ``SimNet`` — the orchestration harness: spawns full ``Node``
  instances (the REAL node — chain, mempool, governor, supervision,
  address book; nothing mocked), drives deterministic block production,
  and runs scenarios to assertable convergence in bounded *virtual*
  time.  With ``store_dir`` set, every node persists to a per-host
  fault-injectable store, and the chaos plane's crash primitives
  apply: ``crash_node`` (abrupt death — severed links, no shutdown
  hooks, a torn in-flight append, a stale mempool checkpoint) and
  ``recover_node`` (reboot through the normal resume path) —
  node/chaos.py composes them with every other injector.

Determinism contract: one seed fixes everything observable.  Node
identity and supervision jitter derive from ``NodeConfig.rng_seed``;
link jitter/loss draw from the sim's own seeded RNG; the loop's timer
heap is deterministic for a deterministic program; and the sim hashes
every network event (connects, per-chunk deliveries with CRC, EOFs,
partitions) into a running SHA-256 — two runs of the same scenario with
the same seed produce byte-identical traces, asserted by
tests/test_netsim.py.  (The contract is per-interpreter: set
``PYTHONHASHSEED`` when comparing traces across processes.)

What the sim does NOT model, honestly (docs/ARCHITECTURE.md): real TCP
backpressure (writes are accepted instantly; ``drain()`` never blocks —
the write-buffer gauge the governor reads is bytes in flight on the
link), kernel buffers and Nagle, OS scheduling and the GIL, packet
loss as actual byte loss (the stream is reliable by construction; the
``loss`` knob models retransmission DELAY spikes instead, which is what
loss does to a TCP stream that survives it), and worker-thread latency
(``run_in_executor`` jobs — the mempool checkpoint's ``to_thread``
write — complete synchronously at the submission instant: a real
thread's completion time is wall-clock state the virtual clock cannot
deterministically place, which the round-17 week-long soak proved by
diverging on it).  Real-socket behavior stays covered by the original
suites through ``SocketTransport``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import heapq
import random
import time
import zlib

from p1_tpu.node.transport import Clock, Listener, Transport

__all__ = [
    "LinkProfile",
    "SimLoop",
    "SimNet",
    "SimTransport",
    "SimWallTimeout",
    "VirtualClock",
]

#: Virtual wall-clock anchor (2026-01-01T00:00:00Z): after the genesis
#: timestamp, so simulated nodes assemble sanely-stamped blocks from the
#: first virtual second.
SIM_EPOCH = 1_767_225_600.0

#: Every simulated node listens here; hosts are distinct, so one port
#: serves the whole mesh (and "host:port" peer strings stay readable).
NODE_PORT = 9444

#: Retransmission penalty, in one-way latencies, added per lost
#: "transmission round" (see LinkProfile.loss).
_RETX_PENALTY = 3.0


class SimWallTimeout(RuntimeError):
    """A scenario exceeded its REAL-time budget — a sim bug (livelock at
    constant virtual time), never a legitimate slow run: virtual time is
    free."""


class VirtualClock(Clock):
    """Time as a plain number the event loop advances."""

    __slots__ = ("now",)

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def monotonic(self) -> float:
        return self.now

    def wall(self) -> float:
        return SIM_EPOCH + self.now


class SimLoop(asyncio.SelectorEventLoop):
    """A selector loop under virtual time.

    ``time()`` returns the virtual clock, so every timer the program
    creates (``sleep``, ``wait_for``, ``call_later``) is scheduled in
    virtual seconds; when no callback is immediately ready, ``_run_once``
    jumps the clock straight to the earliest timer instead of sleeping —
    the discrete-event step.  The selector is still polled (timeout 0)
    each iteration, so thread-safe wakeups keep working; a pure
    simulation registers no real I/O, so the poll is a no-op.
    """

    def __init__(self, clock: VirtualClock):
        super().__init__()
        self._sim_clock = clock

    def time(self) -> float:
        return self._sim_clock.now

    def run_in_executor(self, executor, func, *args):
        """Worker jobs complete SYNCHRONOUSLY, at the current virtual
        instant.  A real executor's completion lands via
        ``call_soon_threadsafe`` at whatever virtual time the loop has
        jumped to by then — racing REAL thread latency against virtual
        time, so two identical runs resume the awaiting coroutine at
        different virtual instants and every timer downstream shifts.
        The round-17 longevity soak caught exactly that: a virtual week
        of 30 s-cadence mempool checkpoints (``asyncio.to_thread`` →
        here) made same-seed traces diverge where 30-virtual-second
        chaos schedules had been too short to trip it.  Running the job
        inline is the only timing a virtual clock can assign it
        deterministically; what the sim gives up — modeling worker
        LATENCY — is recorded in the module docstring's honesty list."""
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except BaseException as e:  # delivered to the awaiter, not lost
            fut.set_exception(e)
        return fut

    def _run_once(self):
        if not self._ready and self._scheduled:
            # Mirror the base loop's cancelled-head sweep so the jump
            # target is a timer that will actually run.
            while self._scheduled and self._scheduled[0]._cancelled:
                self._timer_cancelled_count -= 1
                handle = heapq.heappop(self._scheduled)
                handle._scheduled = False
            if self._scheduled:
                when = self._scheduled[0]._when
                if when > self._sim_clock.now:
                    self._sim_clock.now = when
        super()._run_once()


@dataclasses.dataclass(frozen=True)
class LinkProfile:
    """One direction of one link.  Defaults model a fast LAN."""

    #: One-way propagation delay, seconds.
    latency_s: float = 0.001
    #: Uniform extra delay in [0, jitter_s) per chunk, from the sim RNG.
    jitter_s: float = 0.0
    #: Throughput shaping in bits/s (0 = infinite).  Chunks serialize
    #: through the link one after another, so a 8 MB sync reply on a
    #: 10 Mb/s link occupies it for ~6.7 virtual seconds.
    bandwidth_bps: float = 0.0
    #: Per-transmission-round loss probability.  The stream stays
    #: reliable (this is a TCP-like transport): each loss adds a
    #: retransmission delay of ``_RETX_PENALTY`` one-way latencies, drawn
    #: repeatedly while the RNG keeps losing — heavy loss means heavy
    #: tail latency, exactly what it does to a surviving TCP flow.
    loss: float = 0.0


class _SimLink:
    """One direction of a connection: FIFO delivery into the remote
    ``StreamReader`` after the profile's delay model."""

    __slots__ = (
        "_net",
        "src",
        "dst",
        "profile",
        "_reader",
        "_queue",
        "_last_arrival",
        "_clear_at",
        "inflight",
        "_closed",
        "_dead",
    )

    def __init__(self, net, src, dst, profile, reader):
        import collections

        self._net = net
        self.src = src
        self.dst = dst
        self.profile = profile
        self._reader = reader
        #: Chunks in flight, send order.  Each delivery timer pops the
        #: HEAD rather than carrying its own chunk: two timers that land
        #: on the same virtual instant may run in either heap order, and
        #: a byte stream must never reorder for it.
        self._queue = collections.deque()
        self._last_arrival = 0.0  # FIFO floor: stream order is sacred
        self._clear_at = 0.0  # when the shaped link is next idle
        self.inflight = 0  # bytes sent, not yet delivered
        self._closed = False  # no further sends (FIN queued)
        self._dead = False  # delivery side torn down (EOF fed)

    def send(self, data: bytes) -> None:
        if self._closed or self._dead or not data:
            return
        net = self._net
        # Per-link byte accounting (round 23): directed host-pair totals
        # for the relay bandwidth budget.  Pure observation — never
        # touches ``_record``, so trace digests are unchanged by it.
        key = (self.src[0], self.dst[0])
        net.link_bytes[key] = net.link_bytes.get(key, 0) + len(data)
        p = self.profile
        now = net.clock.now
        delay = p.latency_s
        if p.jitter_s:
            delay += p.jitter_s * net._rng.random()
        if p.loss:
            while net._rng.random() < p.loss:
                delay += _RETX_PENALTY * max(p.latency_s, 1e-3)
        ebps = net.host_egress.get(self.src[0], 0.0)
        if ebps:
            # The shared uplink: all of this host's connections contend
            # for one serializer, so a node that floods N copies of a tx
            # pays N serializations back to back.
            estart = max(now, net._egress_clear.get(self.src[0], 0.0))
            now = net._egress_clear[self.src[0]] = (
                estart + 8.0 * len(data) / ebps
            )
        if p.bandwidth_bps:
            start = max(now, self._clear_at)
            self._clear_at = start + 8.0 * len(data) / p.bandwidth_bps
            arrival = self._clear_at + delay
        else:
            arrival = now + delay
        arrival = max(arrival, self._last_arrival)
        self._last_arrival = arrival
        self.inflight += len(data)
        self._queue.append(bytes(data))
        asyncio.get_running_loop().call_at(arrival, self._deliver)

    def _deliver(self) -> None:
        if not self._queue:
            return  # severed: kill() flushed the queue
        data = self._queue.popleft()
        self.inflight -= len(data)
        if self._dead:
            return  # severed while in flight: the bytes died with the link
        self._net._record(
            "rx", self._net.clock.now, self.src, self.dst, len(data),
            zlib.crc32(data),
        )
        self._reader.feed_data(data)

    def close(self) -> None:
        """Graceful FIN: pending bytes still arrive, then EOF."""
        if self._closed or self._dead:
            return
        self._closed = True
        when = max(
            self._net.clock.now + self.profile.latency_s, self._last_arrival
        )
        asyncio.get_running_loop().call_at(when, self._eof)

    def _eof(self) -> None:
        if self._dead:
            return
        self._dead = True
        self._net._record("eof", self._net.clock.now, self.src, self.dst)
        self._reader.feed_eof()

    def kill(self) -> None:
        """Partition sever / local close: immediate EOF, in-flight bytes
        lost (pending deliveries see ``_dead``/an empty queue and
        drop)."""
        if self._dead:
            return
        self._dead = True
        self._closed = True
        self.inflight = 0
        self._queue.clear()
        self._net._record("cut", self._net.clock.now, self.src, self.dst)
        self._reader.feed_eof()


class _SimWriter:
    """The slice of ``asyncio.StreamWriter`` the node and harnesses use.
    Doubles as its own ``.transport`` (``get_write_buffer_size`` /
    ``is_closing`` — the governor's write-queue gauges read bytes in
    flight on the outbound link)."""

    def __init__(self, conn, link, peer_link, peername, sockname):
        self._conn = conn
        self._link = link  # outbound
        self._peer_link = peer_link  # inbound (killed on close)
        self._peername = peername
        self._sockname = sockname
        self._closed = False
        self.transport = self

    # -- transport surface -------------------------------------------------

    def get_write_buffer_size(self) -> int:
        return self._link.inflight

    def is_closing(self) -> bool:
        return self._closed

    # -- writer surface ----------------------------------------------------

    def get_extra_info(self, name, default=None):
        if name == "peername":
            return self._peername
        if name == "sockname":
            return self._sockname
        return default

    def write(self, data: bytes) -> None:
        if not self._closed:
            self._link.send(data)

    async def drain(self) -> None:
        if self._closed:
            raise ConnectionResetError("sim writer closed")
        # No TCP backpressure model (module docstring): writes are
        # accepted instantly and shaped on the link instead.

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._link.close()  # our FIN: pending bytes flush, then EOF
        self._peer_link.kill()  # we stop reading: our reader unblocks now
        self._conn._side_closed()

    async def wait_closed(self) -> None:
        return


class _SimConn:
    """One established connection: two directed links + their writers."""

    def __init__(self, net, src, dst, prof_out, prof_back):
        self.a_addr = src  # (host, port) of the dialer
        self.b_addr = dst
        self.a_reader = asyncio.StreamReader()
        self.b_reader = asyncio.StreamReader()
        self._net = net
        self._open_sides = 2
        link_ab = _SimLink(net, src, dst, prof_out, self.b_reader)
        link_ba = _SimLink(net, dst, src, prof_back, self.a_reader)
        self.a_writer = _SimWriter(self, link_ab, link_ba, dst, src)
        self.b_writer = _SimWriter(self, link_ba, link_ab, src, dst)

    def crosses(self, blocked) -> bool:
        return blocked(self.a_addr[0], self.b_addr[0])

    def sever(self) -> None:
        """A partition cut the wire: both directions die instantly."""
        self.a_writer._link.kill()
        self.b_writer._link.kill()
        self._net._conns.pop(self, None)

    def _side_closed(self) -> None:
        self._open_sides -= 1
        if self._open_sides <= 0:
            self._net._conns.pop(self, None)


class _SimListener(Listener):
    def __init__(self, net, host, port):
        self._net = net
        self._host = host
        self._port = port

    @property
    def port(self) -> int:
        return self._port

    def close(self) -> None:
        self._net._listeners.pop((self._host, self._port), None)

    async def wait_closed(self) -> None:
        return


class _SimHostTransport(Transport):
    """The per-participant facade: binds a source host so the remote
    side's per-host accounting (bans, ADDR budgets, violation scores)
    sees distinct simulated machines."""

    def __init__(self, net, host):
        self._net = net
        self.host = host
        self.clock = net.clock

    async def listen(self, on_conn, host: str, port: int) -> Listener:
        return await self._net._listen(on_conn, host or self.host, port)

    async def connect(self, host, port, local_addr=None):
        return await self._net._connect(self.host, host, port, local_addr)


class SimTransport:
    """The in-memory network: listeners, links, partitions, the trace.

    Hand each participant ``host(name)`` — a ``Transport`` facade bound
    to that source address.  ``set_profile`` shapes pairs of hosts
    (asymmetric by default direction; ``symmetric=True`` sets both);
    unprofiled pairs use ``default_profile``.
    """

    def __init__(
        self,
        clock: VirtualClock | None = None,
        seed: int = 0,
        default_profile: LinkProfile | None = None,
        keep_trace: bool = False,
    ):
        self.clock = clock if clock is not None else VirtualClock()
        self._rng = random.Random((seed << 1) ^ 0x51D0)
        self.default_profile = default_profile or LinkProfile()
        self._profiles: dict[tuple[str, str], LinkProfile] = {}
        self._listeners: dict[tuple[str, int], object] = {}
        #: Live connections in ESTABLISHMENT order (a dict, not a set:
        #: partition severing iterates this, and set order is id()-based
        #: — the one nondeterminism that broke byte-identical traces in
        #: development).
        self._conns: dict[_SimConn, None] = {}
        self._partition: dict[str, int] | None = None
        self._eph = 20000  # deterministic ephemeral source ports
        self._hasher = hashlib.sha256()
        self.events = 0
        self.trace: list[tuple] | None = [] if keep_trace else None
        self._tasks: set[asyncio.Task] = set()
        #: (src_host, dst_host) -> bytes put on that directed link, every
        #: payload chunk counted at ``_SimLink.send`` (round 23's
        #: per-link accounting).  Observation only: reading or resetting
        #: it never perturbs the trace digest.
        self.link_bytes: dict[tuple[str, str], int] = {}
        #: host -> uplink bits/s.  Opt-in per-HOST egress shaping (round
        #: 23): every chunk the host sends — on ANY connection —
        #: serializes through one shared uplink before the per-link
        #: profile applies, which is the physical constraint the relay
        #: bandwidth budget is about (a flooding node pays its degree on
        #: ONE access link, not on ``degree`` independent ones).  Empty
        #: (the default) means infinite uplinks: existing scenarios and
        #: their pinned trace digests are untouched.
        self.host_egress: dict[str, float] = {}
        self._egress_clear: dict[str, float] = {}

    # -- topology ----------------------------------------------------------

    def host(self, name: str) -> _SimHostTransport:
        return _SimHostTransport(self, name)

    def set_profile(
        self, src: str, dst: str, profile: LinkProfile, symmetric: bool = True
    ) -> None:
        self._profiles[(src, dst)] = profile
        if symmetric:
            self._profiles[(dst, src)] = profile

    def profile_between(self, src: str, dst: str) -> LinkProfile:
        return self._profiles.get((src, dst), self.default_profile)

    def blocked(self, a: str, b: str) -> bool:
        p = self._partition
        if p is None:
            return False
        ga, gb = p.get(a), p.get(b)
        # Hosts outside every named group are unconstrained (e.g. an
        # observer added after the cut).
        return ga is not None and gb is not None and ga != gb

    def kill_host(self, host: str) -> None:
        """A host died abruptly (the chaos plane's crash primitive):
        every connection touching it is severed — in-flight bytes die on
        the wire, exactly like a partition cut — and its listeners
        vanish, so reconnect dials are refused until the host comes back
        and listens again.  Recorded in the trace: a crash is an
        observable network event."""
        self._record("kill_host", self.clock.now, host)
        for key in [k for k in self._listeners if k[0] == host]:
            del self._listeners[key]
        for conn in [
            c
            for c in self._conns
            if c.a_addr[0] == host or c.b_addr[0] == host
        ]:
            conn.sever()

    def partition(self, *groups) -> None:
        """Split the network: hosts in different groups can neither dial
        each other nor keep existing connections (those are severed —
        in-flight bytes die on the wire, like a cut cable)."""
        mapping: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for h in group:
                mapping[h] = gi
        self._partition = mapping
        self._record(
            "partition", self.clock.now,
            tuple(sorted(mapping.values()).count(i) for i in range(len(groups))),
        )
        for conn in [c for c in self._conns if c.crosses(self.blocked)]:
            conn.sever()

    def heal(self) -> None:
        self._partition = None
        self._record("heal", self.clock.now)

    # -- the event trace ---------------------------------------------------

    def _record(self, *fields) -> None:
        self._hasher.update(repr(fields).encode())
        self.events += 1
        if self.trace is not None:
            self.trace.append(fields)

    def trace_digest(self) -> str:
        """Running SHA-256 over every event so far — the byte-identity
        witness two same-seed runs must agree on."""
        return self._hasher.hexdigest()

    # -- transport internals ----------------------------------------------

    async def _listen(self, on_conn, host: str, port: int) -> Listener:
        if port == 0:
            self._eph += 1
            port = self._eph
        key = (host, port)
        if key in self._listeners:
            raise OSError(f"sim: address already in use: {host}:{port}")
        self._listeners[key] = on_conn
        self._record("listen", self.clock.now, host, port)
        return _SimListener(self, host, port)

    async def _connect(self, src_host, dst_host, dst_port, local_addr=None):
        if local_addr is not None:
            src_host = local_addr[0]
        prof_out = self.profile_between(src_host, dst_host)
        # The dial costs one round trip either way (SYN, then accept or
        # refusal coming back).
        await asyncio.sleep(2.0 * prof_out.latency_s)
        on_conn = self._listeners.get((dst_host, dst_port))
        if on_conn is None or self.blocked(src_host, dst_host):
            self._record("refused", self.clock.now, src_host, dst_host, dst_port)
            raise ConnectionRefusedError(
                f"sim: {dst_host}:{dst_port} unreachable from {src_host}"
            )
        self._eph += 1
        src = (src_host, self._eph)
        dst = (dst_host, dst_port)
        conn = _SimConn(
            self, src, dst, prof_out, self.profile_between(dst_host, src_host)
        )
        self._conns[conn] = None
        self._record("connect", self.clock.now, src, dst)
        task = asyncio.get_running_loop().create_task(
            on_conn(conn.b_reader, conn.b_writer)
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return conn.a_reader, conn.a_writer


class SimNet:
    """Scenario harness: full ``Node`` instances over a ``SimTransport``
    under a ``SimLoop``, with deterministic block production.

    Mining is driven by the scenario, not by node mine loops: the
    per-node ``run_in_executor`` nonce search would reintroduce real
    threads (and their scheduling nondeterminism) into a simulation
    whose whole point is reproducibility.  ``mine_on(node)`` assembles
    against the node's own chain/mempool (the REAL ``_assemble`` path —
    virtual-wall timestamps, pool selection, retarget clamps), seals
    synchronously with the deterministic cpu backend (nonce space
    scanned from 0), and injects through ``_handle_block`` so gossip,
    compact relay, orphan handling, and reorgs all run for real.
    """

    def __init__(
        self,
        seed: int = 0,
        difficulty: int = 8,
        default_profile: LinkProfile | None = None,
        keep_trace: bool = False,
        store_dir=None,
        telemetry: bool = True,
        segmented_store: bool = False,
        segment_bytes: int = 1 << 14,
        pipeline_workers: int = 0,
    ):
        from pathlib import Path

        from p1_tpu.hashx import get_backend
        from p1_tpu.miner import Miner

        self.seed = seed
        self.difficulty = difficulty
        #: Default for every spawned node's ``config.telemetry`` —
        #: recording reads only the VIRTUAL clock, so flipping this must
        #: not move the trace digest (the observer contract the
        #: determinism pair in tests/test_telemetry.py pins).
        self.telemetry = telemetry
        self.clock = VirtualClock()
        self.net = SimTransport(
            self.clock,
            seed=seed,
            default_profile=default_profile,
            keep_trace=keep_trace,
        )
        self.rng = random.Random(seed)
        self.nodes: dict[str, object] = {}
        self.configs: dict[str, object] = {}
        #: ``store_dir`` gives every node a real on-disk ChainStore
        #: (one ``<host>.dat`` per node, always a fault-injectable
        #: ``FaultStore``) — the substrate crash/recovery scenarios
        #: need: a crashed node's surviving state IS its files.
        self.store_dir = Path(store_dir) if store_dir is not None else None
        #: ``segmented_store`` gives every node the SEGMENTED layout
        #: (chain/segstore.py) behind the same FaultStore seam — tiny
        #: ``segment_bytes`` so a few mined blocks cross roll
        #: boundaries.  The chaos plane (node/chaos.py) runs its whole
        #: schedule corpus over segmented stores this way.
        self.segmented_store = segmented_store
        self.segment_bytes = segment_bytes
        #: Default for every spawned node's ``config.pipeline_workers``
        #: (node/pipeline.py, round 19).  Under the virtual loop a lane
        #: submission completes synchronously (``SimLoop.run_in_executor``
        #: above), so flipping this must not move the trace digest —
        #: the staging determinism pair in tests/test_pipeline.py pins
        #: exactly that, the same observer contract as ``telemetry``.
        self.pipeline_workers = pipeline_workers
        #: host -> live FaultStore (chaos events re-arm plans on these).
        self.stores: dict[str, object] = {}
        #: Hosts currently dead from ``crash_node`` (host -> the dead
        #: Node object, kept for post-mortem assertions in tests).
        self.crashed: dict[str, object] = {}
        self._miner = Miner(backend=get_backend("cpu"), chunk=1 << 16)

    # -- lifecycle ---------------------------------------------------------

    @staticmethod
    def host_name(i: int) -> str:
        return f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"

    def _make_store(self, host: str, plan=None):
        """A fresh FaultStore over the host's on-disk log (None when the
        host is configured storeless).  Always a FaultStore, even with a
        healthy plan: chaos events arm disk faults on live stores, and a
        recover must hand the new process the same injectable seam."""
        config = self.configs[host]
        if not config.store_path:
            self.stores.pop(host, None)
            return None
        from p1_tpu.chain.testing import FaultStore, SegFaultStore

        if self.segmented_store:
            store = SegFaultStore(
                config.store_path,
                plan=plan,
                segment_bytes=self.segment_bytes,
            )
        else:
            store = FaultStore(config.store_path, plan=plan)
        self.stores[host] = store
        return store

    async def add_node(
        self, name: str | None = None, peers=(), store_plan=None, **cfg
    ):
        """Spawn and start one full node.  ``peers`` are host names (or
        explicit "host:port" strings); defaults keep the sim lean —
        mining off (scenario-driven), no mempool TTL loop, seeded
        identity.  With ``store_dir`` set (or an explicit ``store_path``
        in ``cfg``), the node persists to a real on-disk FaultStore;
        ``store_plan`` scripts its initial disk pathology."""
        from p1_tpu.config import NodeConfig
        from p1_tpu.node.node import Node

        host = name if name is not None else self.host_name(len(self.nodes))
        cfg.setdefault("difficulty", self.difficulty)
        cfg.setdefault("mine", False)
        cfg.setdefault("mempool_ttl_s", 0.0)
        cfg.setdefault("rng_seed", self.rng.getrandbits(48))
        cfg.setdefault("telemetry", self.telemetry)
        cfg.setdefault("pipeline_workers", self.pipeline_workers)
        if self.store_dir is not None:
            cfg.setdefault("store_path", str(self.store_dir / f"{host}.dat"))
        peer_strs = tuple(
            p if ":" in p else f"{p}:{NODE_PORT}" for p in peers
        )
        config = NodeConfig(
            host=host, port=NODE_PORT, peers=peer_strs, **cfg
        )
        self.configs[host] = config
        node = Node(
            config,
            miner=self._miner,
            transport=self.net.host(host),
            store=self._make_store(host, plan=store_plan),
        )
        self.nodes[host] = node
        await node.start()
        return node

    async def stop_node(self, host: str) -> None:
        node = self.nodes.pop(host)
        await node.stop()

    async def restart_node(self, host: str):
        """Churn: bring a previously stopped host back with the SAME
        config (and so the same seed-derived identity).  GRACEFUL
        restart: the predecessor's ``stop()`` ran every shutdown hook
        (mempool checkpoint, address book, store close) — contrast
        ``crash_node``/``recover_node``, which skip them all."""
        from p1_tpu.node.node import Node

        node = Node(
            self.configs[host],
            miner=self._miner,
            transport=self.net.host(host),
            store=self._make_store(host),
        )
        self.nodes[host] = node
        await node.start()
        return node

    async def crash_node(self, host: str, torn: int = 0):
        """Kill a node ABRUPTLY — the process-death model, no graceful
        shutdown anywhere on the path:

        - the wire dies first (``kill_host``): every connection is
          severed with bytes in flight, reconnect dials refuse until
          the host listens again;
        - every task is cancelled with no close hooks — no mempool
          save, no address-book save, no final store sync: whatever the
          last periodic checkpoint wrote is what the disk holds (stale
          by up to one housekeeping interval, exactly like a real
          crash);
        - ``torn > 0`` tears an in-flight store append at the kill
          point through the FaultStore torn-write seam: the node's
          current assembly candidate dies ``torn``-bytes into its
          record — the on-disk artifact a power cut mid-append leaves,
          which ``recover_node``'s normal resume must truncate;
        - file handles close (the writer flock releases — a dead
          process holds no locks), buffers are NOT flushed gracefully
          (the store flushes per append by design, so acknowledged
          records are already on disk — the durability contract under
          test).

        The dead Node object is kept in ``self.crashed[host]`` for
        post-mortem assertions."""
        node = self.nodes.pop(host)
        self.net._record("crash", self.clock.now, host, torn)
        self.net.kill_host(host)
        node._running = False
        node._abort_inflight_search()
        tasks = [*node._tasks, *node._sessions]
        for task in tasks:
            task.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        node._tasks.clear()
        node._sessions.clear()
        if node._mempool_io is not None:
            # The checkpoint WRITE runs in a real thread the event loop
            # cannot cancel; wait it out so the post-crash disk state is
            # a deterministic function of virtual time (either the
            # checkpoint fully landed — tmp+rename is atomic — or it
            # was never started), not a race against the wall clock.
            await asyncio.gather(node._mempool_io, return_exceptions=True)
        if node.store is not None:
            if torn > 0:
                self._tear_append(node, torn)
            node.store.close()
        self.crashed[host] = node
        return node

    def _tear_append(self, node, torn: int) -> None:
        """Die ``torn`` bytes into appending the node's current assembly
        candidate — the in-flight record a mid-append crash tears.  Runs
        through the FaultStore torn-write plan (chain/testing.py), so
        the partial bytes genuinely reach the file the way the harness's
        storage suites model it."""
        from p1_tpu.chain.testing import StoreFaultPlan

        store = node.store
        candidate = node._assemble()
        # A full record is 4 (length) + payload + 4 (CRC) bytes; clamp
        # the tear strictly inside it so the artifact is always an
        # INCOMPLETE record (at minimum the CRC trailer is missing).
        record_len = len(candidate.serialize()) + 8
        torn_bytes = 1 + (torn - 1) % (record_len - 1)
        store.plan = StoreFaultPlan(
            fail_write_at=store.writes + 1, torn_bytes=torn_bytes
        )
        try:
            store.append(candidate)
        except OSError:
            pass  # the point: the append died mid-write
        finally:
            store.plan = StoreFaultPlan()

    async def recover_node(self, host: str):
        """Reboot a crashed host from the same on-disk state through the
        NORMAL resume path — ``Node.start()``'s store acquire (torn-tail
        truncation, corruption quarantine/heal), validated chain replay,
        and full-admission mempool reload.  Nothing about the boot knows
        it follows a crash; that is the contract under test."""
        assert host in self.crashed, f"{host} did not crash"
        del self.crashed[host]
        from p1_tpu.node.node import Node

        self.net._record("recover", self.clock.now, host)
        node = Node(
            self.configs[host],
            miner=self._miner,
            transport=self.net.host(host),
            store=self._make_store(host),
        )
        self.nodes[host] = node
        await node.start()
        return node

    async def stop_all(self) -> None:
        for host in list(self.nodes):
            await self.stop_node(host)

    def run(self, coro, debug: bool = False):
        """Run ``coro`` to completion on a fresh ``SimLoop`` (the
        scenario entry point — one virtual world per call)."""
        loop = SimLoop(self.clock)
        loop.set_debug(debug)
        asyncio.set_event_loop(loop)
        try:
            return loop.run_until_complete(coro)
        finally:
            try:
                pending = asyncio.all_tasks(loop)
                for task in pending:
                    task.cancel()
                if pending:
                    loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True)
                    )
                loop.run_until_complete(loop.shutdown_asyncgens())
            finally:
                asyncio.set_event_loop(None)
                loop.close()

    # -- scenario drivers --------------------------------------------------

    async def mine_on(self, node, spacing_s: float = 0.0):
        """Deterministically mine ONE block on ``node`` and inject it
        (gossip fans out through the sim links).  ``spacing_s`` of
        virtual time afterwards lets propagation land before the next
        block — the scenario's block cadence knob."""
        from p1_tpu.core.block import Block

        candidate = node._assemble()
        sealed = self._miner.search_nonce(candidate.header)
        assert sealed is not None, "nonce space exhausted (raise difficulty?)"
        block = Block(sealed, candidate.txs)
        node.metrics.blocks_mined += 1
        await node._handle_block(block, origin=None)
        if spacing_s:
            await asyncio.sleep(spacing_s)
        return block

    async def run_until(
        self,
        cond,
        timeout: float,
        step: float = 0.05,
        wall_limit_s: float | None = None,
    ) -> bool:
        """Advance virtual time until ``cond()`` or ``timeout`` virtual
        seconds pass.  ``wall_limit_s`` guards REAL time: virtual time
        is free, so exceeding it means the sim livelocked — a bug, and
        ``SimWallTimeout`` says so loudly."""
        deadline = self.clock.now + timeout
        wall0 = time.monotonic()
        while self.clock.now < deadline:
            if cond():
                return True
            if (
                wall_limit_s is not None
                and time.monotonic() - wall0 > wall_limit_s
            ):
                raise SimWallTimeout(
                    f"scenario burned {wall_limit_s:.0f}s of wall time at "
                    f"virtual t={self.clock.now:.1f}"
                )
            await asyncio.sleep(step)
        return bool(cond())

    def links_up(self) -> bool:
        """True once every CONFIGURED dial is a registered session: the
        sum of peer counts reaches twice the configured edge count (each
        established dial registers a _Peer on both ends).  The strong
        mesh-formation condition for static topologies — ``peer_count
        >= 1`` alone lets a scenario start while handshakes are still in
        flight, which is a (real, now handled) race, not the steady
        state most scenarios mean to begin from."""
        expected = 2 * sum(
            len(c.peer_addrs()) for c in self.configs.values()
        )
        return (
            sum(n.peer_count() for n in self.nodes.values()) >= expected
        )

    # -- invariants --------------------------------------------------------

    def tips(self, hosts=None) -> set[bytes]:
        hosts = self.nodes if hosts is None else hosts
        return {self.nodes[h].chain.tip_hash for h in hosts}

    def converged(self, hosts=None) -> bool:
        return len(self.tips(hosts)) == 1

    def heights(self) -> list[int]:
        return [n.chain.height for n in self.nodes.values()]

    def ledger_conserved(self) -> bool:
        """The byzantine soak's containment invariant at sim scale: with
        a coinbase in every block, each node's ledger must sum to
        exactly BLOCK_REWARD x its height — across every partition,
        reorg, and churn cycle."""
        from p1_tpu.core.tx import BLOCK_REWARD

        return all(
            sum(n.chain.balances_snapshot().values())
            == BLOCK_REWARD * n.chain.height
            for n in self.nodes.values()
        )

    def trace_digest(self) -> str:
        return self.net.trace_digest()
