"""The `p1 node` process runner: args namespace -> configured Node loop.

Extracted from ``cli.py`` (which keeps only parsing + dispatch): builds
the ``NodeConfig``, runs the node through its deadline/duration/status
loop, and owns the quiesce dance and the ``--store-degraded-exit``
watch.  `p1 pod`'s leader reuses it with its own arg namespace and a
``PodMiner`` injected.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time


async def run_node(args, miner=None) -> int:
    from p1_tpu.config import NodeConfig
    from p1_tpu.node import Node

    config = NodeConfig(
        difficulty=args.difficulty,
        backend=args.backend,
        host=args.host,
        port=args.port,
        peers=tuple(args.peers),
        mine=not args.no_mine,
        store_path=args.store,
        batch=args.batch,
        chunk=args.chunk,
        miner_id=args.miner_id,
        # getattr: `p1 pod` reuses this runner with its own arg namespace,
        # which has no retarget or compact-gossip flags (pod mining is
        # fixed-difficulty — config 5's shape).
        retarget_window=getattr(args, "retarget_window", 0),
        target_spacing=getattr(args, "target_spacing", 0),
        compact_gossip=not getattr(args, "no_compact_gossip", False),
        target_peers=getattr(args, "target_peers", 0),
        mempool_ttl_s=getattr(args, "mempool_ttl", 3600.0),
        handshake_timeout_s=getattr(args, "handshake_timeout", 10.0),
        ping_interval_s=getattr(args, "ping_interval", 60.0),
        pong_timeout_s=getattr(args, "pong_timeout", 20.0),
        sync_stall_timeout_s=getattr(args, "sync_stall_timeout", 10.0),
        sync_attempts_max=getattr(args, "sync_attempts", 8),
        revalidate_store=getattr(args, "revalidate_store", False),
        verify_workers=getattr(args, "verify_workers", 0),
        pipeline_workers=getattr(args, "pipeline_workers", 0),
        sig_backend=getattr(args, "sig_backend", "auto"),
        store_degraded_exit=getattr(args, "store_degraded_exit", False),
        # Overload resilience (node/governor.py): the watermark flag is
        # MB on the command line, bytes in the config.
        admission_control=not getattr(args, "no_admission_control", False),
        mem_watermark_bytes=int(
            getattr(args, "mem_watermark_mb", 0.0) * (1 << 20)
        ),
        body_cache_blocks=getattr(args, "body_cache", 0),
        telemetry=not getattr(args, "no_telemetry", False),
        # Archive-scale layout (chain/segstore.py): segment size is MB
        # on the command line, bytes in the config.
        store_segment_bytes=int(
            getattr(args, "store_segment_mb", 0.0) * (1 << 20)
        ),
        prune_keep_blocks=getattr(args, "prune", 0),
        snapshot_interval=getattr(args, "snapshot_interval", 0),
    )
    node = Node(config, miner=miner)
    await node.start()
    # --store-degraded-exit watch: the node signals instead of exiting
    # itself so teardown (final status line, mempool save, store close)
    # still runs through the one path below.  Exit code 4.
    fatal = asyncio.ensure_future(node.store_fatal.wait())
    rc = 0
    try:
        if args.deadline is not None or args.duration is not None:
            if args.deadline == "stdin":
                print(json.dumps({"ready": node.port}), flush=True)
                loop = asyncio.get_running_loop()
                line = await loop.run_in_executor(None, sys.stdin.readline)
                deadline = float(line.strip())
            elif args.deadline is not None:
                deadline = float(args.deadline)
            else:
                deadline = time.time() + args.duration
            window = max(0.0, deadline - time.time())
            # Through the node's identity adapter (node/telemetry.py
            # NodeLogAdapter): in a multi-node process (`p1 net`,
            # netharness workers sharing stderr) this line must say
            # WHICH node's window it is.
            node.log.info("mining window: %.2fs until deadline", window)
            await asyncio.wait({fatal}, timeout=window)
            if fatal.done():
                rc = 4
            else:
                # Quiesce: stop producing, then wait for the gossip
                # backlog to drain (GIL-bound mining starves the event
                # loop, so a fixed sleep can undershoot): exit once the
                # chain has been stable for a full second, or after 20s
                # regardless.
                await node.stop_mining()
                await node.request_sync()
                t_end = time.monotonic() + 20.0
                stable = (node.chain.tip_hash, node.metrics.blocks_accepted)
                stable_since = time.monotonic()
                while time.monotonic() < t_end:
                    await asyncio.sleep(0.1)
                    now_state = (
                        node.chain.tip_hash,
                        node.metrics.blocks_accepted,
                    )
                    if now_state != stable:
                        stable, stable_since = now_state, time.monotonic()
                        await node.request_sync()
                    elif time.monotonic() - stable_since >= 1.0:
                        break
        else:
            while True:
                await asyncio.wait({fatal}, timeout=args.status_interval)
                if fatal.done():
                    rc = 4
                    break
                print(json.dumps(node.status()), flush=True)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        fatal.cancel()
        print(json.dumps(node.status()), flush=True)
        await node.stop()
    return rc
