from p1_tpu.node.client import send_tx
from p1_tpu.node.node import Node, NodeMetrics
from p1_tpu.node.protocol import Hello, MsgType
from p1_tpu.node.transport import SocketTransport, Transport

__all__ = [
    "Node",
    "NodeMetrics",
    "Hello",
    "MsgType",
    "send_tx",
    "SocketTransport",
    "Transport",
]
