from p1_tpu.node.node import Node, NodeMetrics
from p1_tpu.node.protocol import Hello, MsgType

__all__ = ["Node", "NodeMetrics", "Hello", "MsgType"]
