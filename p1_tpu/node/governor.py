"""Node-wide resource governor: overload as a first-class, survivable state.

The third leg of the degradation triad.  Sync-stall failover (round 6)
handles peers that starve us; the storage durability layer (round 7)
handles a disk that fails us; this module handles peers that give us TOO
MUCH — protocol-valid block/tx/query floods that, before it existed, could
grow node memory (the unbounded in-RAM chain index above all) or starve
honest traffic until the process OOMed.  Bitcoin Core's answer is the
model (PAPERS.md lineage: ``-maxmempool`` eviction, orphan-pool caps,
BIP152 bandwidth discipline): every queue bounded, every peer budgeted,
degradation explicit.  Three layers:

- **Admission control** — per-PEER token buckets, one per traffic class
  (``blocks`` / ``txs`` / ``queries``), generalizing the per-host ADDR
  budget that already guards the address book.  An over-budget frame is
  dropped at the dispatch door (the chain/mempool/reply machinery never
  sees it), and sustained flooding past the budget escalates to the
  node's existing misbehavior score — one violation per
  ``DROPS_PER_VIOLATION`` drops, so an honest burst that clips the
  budget by a few frames is never scored while a flood earns its ban.
  Solicited replies (BLOCKS, MEMPOOL, HEADERS, BLOCKTXN, ...) are never
  charged: we asked for them, and charging them would let the budget
  starve our own IBD.

- **Memory-bounded operation** — the chain evicts block *bodies* from
  the RAM index once they are safely in the append-only store
  (``Chain.evict_bodies`` + ``ChainStore.read_body``), keeping headers
  and metadata resident; anything evicted is refetched on demand.  The
  governor owns the policy (how many recent bodies stay hot, when to
  sweep); the mechanism lives in chain/store.

- **Load shedding** — above a high watermark on the node's *accounted*
  memory gauge (resident chain bodies + pending pool bytes + peer write
  buffers — deterministic and reversible, unlike OS RSS, which CPython's
  allocator rarely returns) the node enters a SHED state mirroring the
  storage layer's serve-only mode: low-priority traffic (tx gossip,
  mempool pages, address chatter, fee/account queries) is dropped,
  consensus-critical service (headers, blocks, proofs, block ingest)
  keeps running, and mining pauses.  Hysteresis: NORMAL resumes only
  below ``low_fraction`` x the watermark, so the state can't flap at the
  boundary.

Pure state machines over an injectable clock (testable without
sleeping), like ``node/supervision.py``; the node owns every send,
every score, and the gauge computation.
"""

from __future__ import annotations

import enum
import time

__all__ = [
    "TokenBucket",
    "PeerBudget",
    "ResourceGovernor",
    "OverloadState",
    "CLASS_BLOCKS",
    "CLASS_TXS",
    "CLASS_QUERIES",
]

#: Traffic classes.  ``blocks`` = unsolicited block pushes (BLOCK,
#: CBLOCK); ``txs`` = unsolicited transaction pushes (TX); ``queries`` =
#: everything a peer asks us to compute or serve (GETBLOCKS, GETHEADERS,
#: GETMEMPOOL, GETACCOUNT, GETPROOF, GETFEES, GETADDR, GETBLOCKTXN,
#: GETSTATUS).  ADDR keeps its own dedicated per-host budget (node.py
#: ``_addr_budgets``) — it guards a different resource (the address
#: book), with different crediting rules.
CLASS_BLOCKS = "blocks"
CLASS_TXS = "txs"
CLASS_QUERIES = "queries"

#: (refill rate tokens/s, burst cap) per class.  Sized generously above
#: any honest peer — and the blocks class is additionally REFUNDED for
#: every push that connects as a new block (``PeerBudget.refund``), so
#: an honest miner never exhausts it no matter how fast the mesh mines:
#: what the refill rate must actually cover is the honest *duplicate*
#: rate, the relay race where several peers push the same block and all
#: but the first arrival is a (charged) dup.  A 3-node localhost
#: byzantine soak at difficulty 12 measures ~95 dup/s per peer at
#: ~190 blocks/s network-wide — the 128/s refill sits above that
#: regime's ceiling while a replay flood (thousands/s of the same
#: block) still hits the cliff in under a second past the burst.  Tx
#: gossip forwards each admission once; queries come one per sync round.
DEFAULT_RATES: dict[str, tuple[float, float]] = {
    CLASS_BLOCKS: (128.0, 1024.0),
    CLASS_TXS: (64.0, 1024.0),
    CLASS_QUERIES: (32.0, 256.0),
}

#: Over-budget drops in one class before ONE misbehavior violation is
#: charged.  An honest burst clips the budget by a handful of frames at
#: worst; a flood crosses this every second or two and earns the
#: existing 3-violations ban.
DROPS_PER_VIOLATION = 64

#: Per-peer outbound write-buffer cap, bytes.  A peer that sends queries
#: but never reads replies grows OUR transport buffer — the write-queue
#: squat.  Past the cap the peer is disconnected: the data it refused to
#: read is re-fetchable, the memory is not.  Comfortably above one
#: full sync reply (SYNC_BYTES = 8 MB) plus gossip slack.
WRITE_QUEUE_MAX = 12 << 20

#: Gossip (best-effort) sends additionally skip peers whose buffer is
#: already past this softer bound — no reason to queue a push behind
#: megabytes of unread replies; the peer heals via locator sync.
WRITE_QUEUE_GOSSIP_MAX = 2 << 20

#: Hard cap on compact-block reconstructions a single peer may hold
#: open.  The global FIFO (MAX_PENDING_CBLOCKS) bounds the total; this
#: bounds how much of it one peer can squat — each slot pins a partially
#: rebuilt block (up to a full block's transactions) in RAM.
PENDING_CBLOCKS_PER_PEER = 8


class OverloadState(enum.Enum):
    NORMAL = "normal"
    SHED = "shed"


class TokenBucket:
    """The refilled token bucket, extracted from the ADDR-budget inline
    lists into a primitive with testable invariants:

    - ``tokens`` never exceeds ``burst`` through refill alone, and never
      exceeds ``grant_cap`` through grants;
    - refill accrues at ``rate`` tokens/s from the last observation and
      never runs backward (a clock that stalls refills nothing);
    - credit sitting above ``burst`` (solicited grants) is never clawed
      back by a refill observation — the ADDR lesson (ADVICE r5).
    """

    __slots__ = ("rate", "burst", "grant_cap", "tokens", "stamp", "_clock")

    def __init__(
        self,
        rate: float,
        burst: float,
        *,
        grant_cap: float | None = None,
        clock=time.monotonic,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self.grant_cap = float(grant_cap) if grant_cap is not None else 4 * self.burst
        self.tokens = self.burst
        self._clock = clock
        self.stamp = clock()

    def _refill(self) -> None:
        now = self._clock()
        if self.tokens < self.burst:
            elapsed = max(0.0, now - self.stamp)
            self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self.stamp = now

    def take(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens if available; False (and no spend) if not."""
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def grant(self, n: float) -> None:
        """ADD solicited credit (bounded by ``grant_cap``) — additive,
        not set-to-max, for the same reason as the ADDR budget: two
        solicited replies in flight must not race for one refill."""
        self._refill()
        self.tokens = min(self.grant_cap, self.tokens + n)

    def peek(self) -> float:
        self._refill()
        return self.tokens


class PeerBudget:
    """One peer's admission state: a bucket per traffic class plus the
    drop tallies that escalate to misbehavior scoring."""

    __slots__ = ("buckets", "dropped", "_pending_violation")

    def __init__(self, rates=None, clock=time.monotonic):
        rates = DEFAULT_RATES if rates is None else rates
        self.buckets = {
            cls: TokenBucket(rate, burst, clock=clock)
            for cls, (rate, burst) in rates.items()
        }
        self.dropped = {cls: 0 for cls in self.buckets}
        self._pending_violation = {cls: 0 for cls in self.buckets}

    def admit(self, cls: str) -> bool:
        """True = within budget.  False = drop the frame; the counters
        advance and ``owes_violation`` may fire."""
        if self.buckets[cls].take():
            return True
        self.dropped[cls] += 1
        self._pending_violation[cls] += 1
        return False

    def refund(self, cls: str) -> None:
        """Return one admission charge — the node refunds a pushed block
        that connected as NEW: PoW makes new blocks self-limiting (an
        attacker cannot mint them faster than the honest mesh), so
        refunding them keeps the budget a pure duplicate/spam throttle
        that no honest mining rate can exhaust."""
        self.buckets[cls].grant(1.0)

    def owes_violation(self, cls: str) -> bool:
        """True once per ``DROPS_PER_VIOLATION`` drops in ``cls`` —
        consumed: the caller charges the misbehavior score exactly once."""
        if self._pending_violation[cls] >= DROPS_PER_VIOLATION:
            self._pending_violation[cls] = 0
            return True
        return False


class ResourceGovernor:
    """The node-wide overload state machine + admission front door.

    The node computes the memory gauge (it owns the chain, the pool, and
    the sockets) and calls ``observe(tracked_bytes)`` from its tick
    loops; everything else is bookkeeping over that number and the
    per-peer budgets.
    """

    def __init__(
        self,
        *,
        watermark_bytes: int = 0,
        low_fraction: float = 0.8,
        admission: bool = True,
        rates: dict[str, tuple[float, float]] | None = None,
        write_queue_max: int = WRITE_QUEUE_MAX,
        clock=time.monotonic,
    ):
        #: High watermark on the accounted gauge; 0 disables shedding
        #: (admission control and write-queue caps stay on — they are
        #: free and bound per-peer resources regardless).
        self.watermark_bytes = int(watermark_bytes)
        self.low_watermark_bytes = int(low_fraction * self.watermark_bytes)
        self.admission = admission
        self.rates = DEFAULT_RATES if rates is None else rates
        self.write_queue_max = int(write_queue_max)
        self._clock = clock
        self.state = OverloadState.NORMAL
        #: Last observed gauge (surfaced by status()).
        self.tracked_bytes = 0
        #: Peak of the gauge over the governor's lifetime (soak assertions).
        self.tracked_peak_bytes = 0
        # -- counters (mirrored into NodeMetrics by the node) --
        self.sheds = 0  # NORMAL -> SHED transitions
        self.shed_drops = 0  # frames dropped because state is SHED
        self.admission_drops = {cls: 0 for cls in self.rates}
        self.write_queue_drops = 0  # gossip sends skipped (soft bound)
        self.peers_dropped_squat = 0  # sessions ended at the hard cap
        self.cblock_slot_drops = 0  # per-peer reconstruction cap hits

    # -- admission ---------------------------------------------------------

    def budget(self) -> PeerBudget:
        """A fresh per-peer budget (the node hangs it on the session)."""
        return PeerBudget(self.rates, clock=self._clock)

    def admit(self, budget: PeerBudget, cls: str) -> bool:
        """Admission verdict for one frame of class ``cls``."""
        if not self.admission:
            return True
        if budget.admit(cls):
            return True
        self.admission_drops[cls] += 1
        return False

    # -- load shedding -----------------------------------------------------

    @property
    def shedding(self) -> bool:
        return self.state is OverloadState.SHED

    def observe(self, tracked_bytes: int) -> bool:
        """Feed one gauge observation; returns True when the state
        changed (the node logs transitions)."""
        self.tracked_bytes = int(tracked_bytes)
        if self.tracked_bytes > self.tracked_peak_bytes:
            self.tracked_peak_bytes = self.tracked_bytes
        if self.watermark_bytes <= 0:
            return False
        if (
            self.state is OverloadState.NORMAL
            and self.tracked_bytes > self.watermark_bytes
        ):
            self.state = OverloadState.SHED
            self.sheds += 1
            return True
        if (
            self.state is OverloadState.SHED
            and self.tracked_bytes < self.low_watermark_bytes
        ):
            self.state = OverloadState.NORMAL
            return True
        return False

    def shed_drop(self) -> None:
        self.shed_drops += 1

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``status()["overload"]`` block."""
        return {
            "state": self.state.value,
            "tracked_bytes": self.tracked_bytes,
            "tracked_peak_bytes": self.tracked_peak_bytes,
            "watermark_bytes": self.watermark_bytes,
            "sheds": self.sheds,
            "shed_drops": self.shed_drops,
            "admission_dropped": dict(self.admission_drops),
            "write_queue_drops": self.write_queue_drops,
            "peers_dropped_squat": self.peers_dropped_squat,
            "cblock_slot_drops": self.cblock_slot_drops,
        }
