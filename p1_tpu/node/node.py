"""The p2p node: gossip, chain sync, and the mining loop.

Capability parity: the reference's "p2p node … gossip network …
longest-chain" (BASELINE.json:5,10); benchmark config 4 is four of these on
localhost.  Design (SURVEY.md §5):

- **Single-threaded asyncio core** — every chain/mempool/peer mutation
  happens on the event loop, so there are no data races by construction.
  The only other thread is the miner's ``run_in_executor`` worker, which
  touches nothing but its own ``HashBackend`` and a ``threading.Event``.
- **Push gossip**: a new block or tx is pushed whole to every peer (the
  chain dedups blocks, the mempool dedups txs, so floods terminate).
  Out-of-order arrivals park in the chain's orphan pool and a GETBLOCKS
  locator sync backfills the gap.
- **Mining abort on new tip**: the in-flight ``search_nonce`` holds a
  ``threading.Event``; any tip movement sets it, the worker returns, and
  the loop reassembles against the new tip — stale work dies in one chunk.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import json
import logging
import os
import random
from pathlib import Path

from p1_tpu.chain import AddResult, AddStatus, Chain, ChainStore
from p1_tpu.chain.store import fsync_dir
from p1_tpu.chain import snapshot as chain_snapshot
from p1_tpu.chain.snapshot import SnapshotError
from p1_tpu.chain.validate import ValidationError, preverify_signatures
from p1_tpu.chain.versionbits import Deployment, VBState, VersionBits
from p1_tpu.config import NodeConfig
from p1_tpu.core import keys
from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.sigcache import SignatureCache
from p1_tpu.core.tx import Transaction
from p1_tpu.mempool import Mempool
from p1_tpu.miner import Miner
from p1_tpu.node import protocol
from p1_tpu.node import reconcile
from p1_tpu.node.governor import (
    CLASS_BLOCKS,
    CLASS_QUERIES,
    CLASS_TXS,
    PENDING_CBLOCKS_PER_PEER,
    WRITE_QUEUE_GOSSIP_MAX,
    ResourceGovernor,
)
from p1_tpu.node.pipeline import NodePipeline, WorkerCrash
from p1_tpu.node.protocol import Hello, MsgType
from p1_tpu.node.supervision import RequestSupervisor
from p1_tpu.node.transport import SOCKET_TRANSPORT, Transport

log = logging.getLogger("p1_tpu.node")

SYNC_BATCH = 500
#: Headers per GETHEADERS reply (80 B each — 2000 is a 160 KB frame).
HEADERS_BATCH = 2000
#: Address-book bound and per-ADDR-reply cap (peer discovery).
MAX_KNOWN_ADDRS = 1024
ADDR_REPLY_MAX = 64
#: Tried-address bucket: addresses verified by a completed handshake.
#: Kept apart from the gossip-fed book so unsolicited ADDR floods can
#: never evict a known-good node (the round-4 eclipse vector) — gossip
#: fills the "new" book, handshakes promote to "tried".
MAX_TRIED_ADDRS = 256
#: Per-peer unsolicited-ADDR budget: a token bucket refilled at
#: ADDR_TOKENS_RATE addresses/second up to one full reply's burst.  Our
#: own GETADDR requests re-credit the responder (solicited replies
#: always fit); a peer streaming ADDR frames on its own initiative is
#: clamped to the refill rate, excess entries silently ignored.
ADDR_TOKENS_MAX = float(ADDR_REPLY_MAX)
ADDR_TOKENS_RATE = 1.0
#: How often the discovery loop checks whether to dial a learned address.
DISCOVERY_INTERVAL_S = 1.0
#: Minimum spacing between repeat GETADDR broadcasts while under target.
READDR_INTERVAL_S = 30.0
#: Server-side cap on a GETFEES sample window — like SYNC_BATCH /
#: HEADERS_BATCH, a peer must not be able to drive O(chain) scans on the
#: event loop by asking big.
FEE_WINDOW_MAX = 1024
#: Filters per GETFILTERS reply (a filter is a few bytes per tx; 1000
#: keeps the frame well under MAX_FRAME even for full blocks).
FILTER_BATCH = 1000
#: Pending compact-block reconstructions awaiting a BLOCKTXN reply.  Small
#: and FIFO-capped: entries exist only for the one GETBLOCKTXN round trip;
#: anything stranded (peer died mid-answer) is evicted by newer blocks and
#: the chain heals through ordinary locator sync.
MAX_PENDING_CBLOCKS = 64
#: Connected-peer cap: the last unbounded per-peer resource (sessions +
#: writer buffers).  Gossip needs a handful of peers; a dialer flood past
#: the cap is refused at handshake time.
MAX_PEERS = 64
#: Cap on inbound connections that have not yet completed HELLO.  A
#: pre-handshake socket never enters ``_peers`` (so MAX_PEERS can't see
#: it) yet holds a session task and transport buffers — without this
#: bound an accept flood grows ``_sessions`` until the handshake timeout
#: fires, and with none it grows forever.  Sized above any honest burst
#: (a whole net restarting dials in well under this).
MAX_HANDSHAKING = 32
#: Byte budget for one BLOCKS reply — safely under protocol.MAX_FRAME so a
#: sync reply is never a frame the receiver is guaranteed to reject.
SYNC_BYTES = 8 << 20
#: Caps for one MEMPOOL sync reply (count and encoded bytes).
MEMPOOL_SYNC_TXS = 2000
MEMPOOL_SYNC_BYTES = 2 << 20
RECONNECT_DELAY_S = 0.5
GOSSIP_SEND_TIMEOUT_S = 5.0
#: Set-reconciliation relay (round 23, Erlay analog).  Per-peer bound on
#: txids queued for the next reconciliation round — overflow floods the
#: oldest entries instead of dropping them (flood is the pressure valve,
#: reconciliation the optimisation, never the other way around).
RECON_PENDING_MAX = 4096
#: Consecutive failed/stalled rounds before a peer is demoted off the
#: recon plane back to plain flooding, and for how long.  Demotion is
#: per-peer and self-healing: a poisoned or broken peer costs us its own
#: link's efficiency, never relay liveness.
RECON_DEMOTE_FAILURES = 3
RECON_DEMOTE_S = 60.0
#: Cap on mempool entries scanned to serve one GETTX fallback fetch when
#: the short-id is no longer in the recon window — bounds the work a
#: hostile GETTX spray can demand.
RECON_GETTX_SCAN_MAX = 4096
#: Misbehavior scoring: a host that commits this many protocol violations
#: (malformed frames, wrong chain/version, bad handshake) within the
#: window is refused at accept time for the ban duration.  Violations are
#: PEER-side faults only — our own refusals (peer cap, self-connect)
#: never count against the remote.
BAN_SCORE_THRESHOLD = 3
BAN_WINDOW_S = 60.0
BAN_DURATION_S = 30.0
#: Bound on tracked misbehaving hosts: an attacker cycling source
#: addresses must not grow node memory one deque per address forever —
#: on overflow, stale entries are pruned first, then oldest-arbitrary.
MAX_TRACKED_HOSTS = 4096
#: Mining POLICY (never consensus): refuse to extend a tip stamped more
#: than this far past local wall time — the hostile-bootstrap-anchor
#: guard (_mining_parent).  30 days: unreachable by honest +1 s/block
#: clock drift at any plausible block count, decades under any attack
#: anchor worth mounting.
ANCHOR_SLACK_S = 30 * 86_400
#: Snapshot chunks per SNAPSHOT reply (server cap AND client ask size):
#: 8 chunks x ~110 KB worst case stays far under MAX_FRAME while keeping
#: a multi-million-account transfer to a few hundred round trips.
SNAPSHOT_BATCH = 8
#: Manifest chunk-count cap a fetching node will accept: bounds the
#: worst-case snapshot RAM a hostile manifest can commit us to
#: (4096 chunks x 4096 accounts = ~16M accounts) before any chunk bytes
#: arrive.
SNAPSHOT_MAX_CHUNKS = 4096

#: Validation states (the snapshot plane's trust posture, surfaced in
#: ``status()["snapshot"]``).  ASSUMED = serving state that came from a
#: verified-but-untrusted snapshot while the real history revalidates in
#: the background; VALIDATED = every block behind the tip was fully
#: validated by this node.
VALIDATED = "validated"
ASSUMED = "assumed"


class _Refused(Exception):
    """Session ended by OUR policy (peer cap, self-connect) — ends the
    connection like a ValueError but never scores against the remote."""


#: Admission classes per message type (node/governor.py).  Only
#: UNSOLICITED traffic is charged: pushes (BLOCK/CBLOCK/TX) and requests
#: that make us compute or serve (the GET* family).  Reply frames
#: (BLOCKS, MEMPOOL, HEADERS, BLOCKTXN, ACCOUNT, PROOF, FEES) are never
#: charged — we asked for them, and charging them would let the budget
#: starve our own IBD.  ADDR keeps its dedicated per-host book budget;
#: PING/PONG stay free — liveness must never be rationed.
_MSG_CLASS = {
    MsgType.BLOCK: CLASS_BLOCKS,
    MsgType.CBLOCK: CLASS_BLOCKS,
    MsgType.TX: CLASS_TXS,
    MsgType.GETBLOCKS: CLASS_QUERIES,
    MsgType.GETHEADERS: CLASS_QUERIES,
    MsgType.GETMEMPOOL: CLASS_QUERIES,
    MsgType.GETACCOUNT: CLASS_QUERIES,
    MsgType.GETPROOF: CLASS_QUERIES,
    MsgType.GETFEES: CLASS_QUERIES,
    MsgType.GETADDR: CLASS_QUERIES,
    MsgType.GETBLOCKTXN: CLASS_QUERIES,
    MsgType.GETSTATUS: CLASS_QUERIES,
    MsgType.GETFILTERS: CLASS_QUERIES,
    MsgType.GETSNAPSHOT: CLASS_QUERIES,
    MsgType.GETMETRICS: CLASS_QUERIES,
    MsgType.GETMAINTAIN: CLASS_QUERIES,
    # The wallet push plane (v14): registering/cancelling a watch and
    # asking for the filter-header commitment chain are requests that
    # make us serve — charged like every other GET*.
    MsgType.SUBSCRIBE: CLASS_QUERIES,
    MsgType.UNSUBSCRIBE: CLASS_QUERIES,
    MsgType.GETFILTERHEADERS: CLASS_QUERIES,
    # The reconciliation plane (v15): opening a round, closing it, and
    # the short-ID fetch all make us compute (a sketch) or serve (TX
    # pushes) — charged like every other request.  The capacity clamp
    # in node/reconcile.py bounds what any single admitted frame can
    # cost; admission bounds how often a peer may present one.
    MsgType.REQRECON: CLASS_QUERIES,
    MsgType.RECONCILDIFF: CLASS_QUERIES,
    MsgType.GETTX: CLASS_QUERIES,
}

#: The OTHER half of the admission contract, spelled out: frames the
#: governor deliberately never charges.  Reply frames (we asked; a
#: budget here would let a slow disk starve our own IBD), the
#: handshake, liveness probes (never rationed), and ADDR, which keeps
#: its dedicated per-host address-book budget instead of a token
#: class.  Every MsgType must appear in exactly one of _MSG_CLASS /
#: _ADMISSION_EXEMPT — the import-time assert below and the
#: wire-contract lint rule both fail a frame type that rides free
#: because somebody FORGOT to classify it (the historical shape:
#: rounds 9–12 each added frames, and an unclassified frame is
#: invisibly maximally permissive).
_ADMISSION_EXEMPT = frozenset(
    {
        MsgType.HELLO,
        MsgType.BLOCKS,
        MsgType.MEMPOOL,
        MsgType.ACCOUNT,
        MsgType.PROOF,
        MsgType.BLOCKTXN,
        MsgType.HEADERS,
        MsgType.FEES,
        MsgType.ADDR,
        MsgType.PING,
        MsgType.PONG,
        MsgType.STATUS,
        MsgType.METRICS,
        MsgType.FILTERS,
        MsgType.SNAPSHOT,
        MsgType.MAINTAIN,
        # Push-plane frames WE emit (EVENT) or asked for
        # (FILTERHEADERS) — charging them would ration our own pushes.
        MsgType.EVENT,
        MsgType.FILTERHEADERS,
        # The sketch reply to OUR REQRECON — solicited like MEMPOOL; an
        # unsolicited one is dropped by the dispatch arm (and scored
        # toward flood demotion), so exemption buys an attacker nothing.
        MsgType.SKETCH,
    }
)
assert (
    set(_MSG_CLASS) | _ADMISSION_EXEMPT == set(MsgType)
    and not set(_MSG_CLASS) & _ADMISSION_EXEMPT
), "every frame type needs exactly one admission classification"

#: Frames dropped while the node is in the SHED overload state.
#: Consensus-critical service — block ingest, headers/blocks/proof
#: serving, liveness, the status probe — stays up; the pool and the
#: address book (pure capacity consumers, fully recoverable from peers
#: later) go quiet first, exactly like the storage layer's serve-only
#: mode sheds ingest but keeps serving.
_SHED_DROPS = frozenset(
    {
        MsgType.TX,
        MsgType.MEMPOOL,
        MsgType.GETMEMPOOL,
        MsgType.GETFEES,
        MsgType.GETACCOUNT,
        MsgType.GETADDR,
        MsgType.ADDR,
        # Snapshot serving is a pure capacity consumer (a joiner can
        # retry any peer later); under SHED it goes quiet with the rest.
        MsgType.GETSNAPSHOT,
        # The telemetry export sheds too — GETSTATUS is the minimal
        # health probe and stays up; the full latency dump is capacity
        # an overloaded node may refuse (scrapers retry).
        MsgType.GETMETRICS,
        # NEW subscriptions shed under overload (a wallet retries any
        # replica); live ones keep degrading down their own ladder, and
        # UNSUBSCRIBE stays up because it frees capacity.
        MsgType.SUBSCRIBE,
        # The whole reconciliation exchange is tx-plane capacity, shed
        # with TX/MEMPOOL: a dropped round degrades to flood (or a
        # retry next interval), never to a lost transaction — the same
        # recoverability argument as the pool itself.  SKETCH is a
        # solicited reply, but unlike BLOCKS the round it answers has
        # its own stall fallback, so shedding it cannot wedge a
        # supervisor.
        MsgType.REQRECON,
        MsgType.SKETCH,
        MsgType.RECONCILDIFF,
        MsgType.GETTX,
    }
)

#: The keep side, spelled out frame by frame: consensus-critical
#: service (block ingest and the sync/relay frames), solicited replies
#: (dropping a reply we asked for would wedge our own supervisors),
#: liveness, and the GETSTATUS health probe — overload must stay
#: observable while it is happening.  Every MsgType must appear in
#: exactly one of _SHED_DROPS / _SHED_KEEPS; the assert and the
#: wire-contract lint rule close the "new frame forgot its SHED
#: classification" hole structurally.
_SHED_KEEPS = frozenset(
    {
        MsgType.HELLO,
        MsgType.BLOCK,
        MsgType.CBLOCK,
        MsgType.GETBLOCKS,
        MsgType.BLOCKS,
        MsgType.GETBLOCKTXN,
        MsgType.BLOCKTXN,
        MsgType.GETHEADERS,
        MsgType.HEADERS,
        MsgType.GETPROOF,
        MsgType.PROOF,
        MsgType.GETFILTERS,
        MsgType.FILTERS,
        MsgType.ACCOUNT,
        MsgType.FEES,
        MsgType.SNAPSHOT,
        MsgType.PING,
        MsgType.PONG,
        MsgType.GETSTATUS,
        MsgType.STATUS,
        MsgType.METRICS,
        # The maintenance plane stays reachable under overload for the
        # same reason GETSTATUS does: online prune/compact/rebase are
        # exactly the operations an operator reaches for WHILE the node
        # is resource-pressured — shedding them would make the fix for
        # overload unavailable during overload.
        MsgType.GETMAINTAIN,
        MsgType.MAINTAIN,
        # UNSUBSCRIBE frees capacity; EVENT/FILTERHEADERS are frames we
        # push or asked for; GETFILTERHEADERS is the commitment-chain
        # probe a wallet uses to decide whether to TRUST us — shedding
        # it during overload would make a loaded replica look like a
        # lying one.
        MsgType.UNSUBSCRIBE,
        MsgType.GETFILTERHEADERS,
        MsgType.EVENT,
        MsgType.FILTERHEADERS,
    }
)
assert (
    _SHED_DROPS | _SHED_KEEPS == set(MsgType)
    and not _SHED_DROPS & _SHED_KEEPS
), "every frame type needs exactly one SHED classification"

#: Relay-byte accounting families (round 23).  Every frame type maps to
#: the bandwidth plane its bytes spend, and every SENT frame is counted
#: at the one send choke point (``_Peer.send``) into a per-msgtype
#: ``relay.bytes.<name>`` telemetry counter plus this family label.
#: The families are what the relay A/B budget reasons over: ``tx`` +
#: ``recon`` together form the tx-relay plane that set reconciliation
#: exists to shrink; ``block`` announces stay flooded by design and are
#: budgeted separately; ``serve``/``push``/``control``/``handshake``
#: are demand-driven, not relay overhead.  Exhaustive like the
#: admission/SHED tables — the assert below and the wire-contract lint
#: rule fail any frame type whose bytes would otherwise silently vanish
#: from the bandwidth budget.
_RELAY_ACCOUNTING: dict = {
    MsgType.HELLO: "handshake",
    MsgType.BLOCK: "block",
    MsgType.CBLOCK: "block",
    MsgType.GETBLOCKS: "block",
    MsgType.BLOCKS: "block",
    MsgType.GETBLOCKTXN: "block",
    MsgType.BLOCKTXN: "block",
    MsgType.GETHEADERS: "block",
    MsgType.HEADERS: "block",
    MsgType.TX: "tx",
    MsgType.GETMEMPOOL: "tx",
    MsgType.MEMPOOL: "tx",
    MsgType.REQRECON: "recon",
    MsgType.SKETCH: "recon",
    MsgType.RECONCILDIFF: "recon",
    MsgType.GETTX: "recon",
    MsgType.GETACCOUNT: "serve",
    MsgType.ACCOUNT: "serve",
    MsgType.GETPROOF: "serve",
    MsgType.PROOF: "serve",
    MsgType.GETFEES: "serve",
    MsgType.FEES: "serve",
    MsgType.GETFILTERS: "serve",
    MsgType.FILTERS: "serve",
    MsgType.GETSNAPSHOT: "serve",
    MsgType.SNAPSHOT: "serve",
    MsgType.GETFILTERHEADERS: "serve",
    MsgType.FILTERHEADERS: "serve",
    MsgType.SUBSCRIBE: "push",
    MsgType.EVENT: "push",
    MsgType.UNSUBSCRIBE: "push",
    MsgType.GETADDR: "control",
    MsgType.ADDR: "control",
    MsgType.PING: "control",
    MsgType.PONG: "control",
    MsgType.GETSTATUS: "control",
    MsgType.STATUS: "control",
    MsgType.GETMETRICS: "control",
    MsgType.METRICS: "control",
    MsgType.GETMAINTAIN: "control",
    MsgType.MAINTAIN: "control",
}
assert set(_RELAY_ACCOUNTING) == set(MsgType) and all(
    _RELAY_ACCOUNTING.values()
), "every frame type needs a relay-byte accounting family"

#: msgtype byte -> telemetry counter name, precomputed so the hot send
#: path never formats a string.
_RELAY_COUNTER_NAME = {
    int(m): "relay.bytes." + m.name.lower() for m in MsgType
}


#: NodeMetrics counter fields, in their historical (dataclass) order.
#: Families, for readers: block/tx flow (mined/accepted/rejected/reorgs,
#: hashes), compact relay (BIP152-style hits/fetches/bytes saved), wire
#: traffic (counted at the one send choke point and the session read
#: loop), liveness probes, lost-task crash observation, request
#: supervision (stalls/failovers/demotions — see node/supervision.py),
#: storage durability (chain/store.py degraded mode), the query serving
#: plane, and untrusted snapshot sync (round 12).
_METRIC_COUNTERS = (
    "blocks_mined",
    "blocks_accepted",
    "blocks_rejected",
    "reorgs",
    "txs_accepted",
    "hashes_done",
    "cblocks_sent",
    "cblocks_received",
    "cblock_tx_hits",
    "cblock_tx_fetched",
    "cblock_bytes_saved",
    "bytes_sent",
    "bytes_received",
    "pings_sent",
    "peers_evicted_idle",
    "task_crashes",
    "sync_stalls",
    "sync_failovers",
    "sync_demotions",
    "sync_exhausted",
    "cblock_fetch_stalls",
    "mempool_sync_stalls",
    "store_errors",
    "store_retries",
    "store_recoveries",
    "store_blocks_deferred",
    "store_segments_pruned",
    "pruned_refusals",
    "proofs_served",
    "filters_served",
    "filter_bytes_served",
    "snapshot_fetches",
    "snapshot_chunks_served",
    "snapshot_flips",
    "snapshot_divergences",
    "snapshot_fallbacks",
    "snapshot_stalls",
    "revalidated_blocks",
    "worker_respawns",
    # The always-on maintenance plane (round 20): zero-downtime
    # operations a long-running node performs on itself while mining
    # and serving, plus the continuous-snapshot economics they enable.
    "rebases",
    "online_prunes",
    "online_compactions",
    "segments_compacted",
    "compaction_records_dropped",
    "snapshot_incremental_builds",
    "snapshot_chunks_reused",
    # Set-reconciliation tx relay (round 23, Erlay analog): rounds we
    # initiated, sketches we served as responder, rounds that decoded,
    # rounds that fell back to flood/paging, peers demoted off the
    # recon plane, and individual txs delivered via reconciliation.
    "recon_rounds",
    "recon_sketches_served",
    "recon_success",
    "recon_fallbacks",
    "recon_demotions",
    "txs_reconciled",
)
#: Float-valued point-in-time fields (mining timing).
_METRIC_GAUGES = ("mine_elapsed_s", "last_block_time_s")


class NodeMetrics:
    """Counters surfaced by ``Node.status()`` (SURVEY.md §5 metrics).

    Round 14: the storage moved onto the telemetry registry
    (node/telemetry.py) so every counter is exportable over GETMETRICS /
    `p1 metrics` / Prometheus — but the ATTRIBUTE surface is unchanged:
    ``metrics.blocks_mined += 1`` still works everywhere it always did
    (``__getattr__``/``__setattr__`` route to the registry), and the
    ``status()`` key contract is pinned byte-for-byte by
    tests/test_telemetry.py.  Unknown attribute names still raise
    AttributeError — a typo must not silently mint a counter.
    """

    __slots__ = ("registry", "propagation_delays_s", "relay_counters")

    def __init__(self, registry=None):
        from p1_tpu.node.telemetry import MetricsRegistry

        object.__setattr__(
            self,
            "registry",
            registry if registry is not None else MetricsRegistry(),
        )
        #: Rolling window of block propagation delays (peer's gossip
        #: send -> our acceptance), seconds — SURVEY §5's "host-side
        #: timing of gossip round-trips".  Bounded; kept as a raw deque
        #: (the historical ``propagation_summary`` contract) alongside
        #: the registry's ``block.propagation_s`` histogram.
        object.__setattr__(
            self, "propagation_delays_s", collections.deque(maxlen=1024)
        )
        #: msgtype byte -> registry Counter for ``relay.bytes.<name>``,
        #: populated lazily on first send of each frame type so the
        #: registry only carries rows for traffic that actually flowed.
        object.__setattr__(self, "relay_counters", {})
        for name in _METRIC_COUNTERS:
            self.registry.counter(name)
        for name in _METRIC_GAUGES:
            self.registry.gauge(name)

    def __getattr__(self, name):
        registry = object.__getattribute__(self, "registry")
        c = registry.counters.get(name)
        if c is not None:
            return c.value
        g = registry.gauges.get(name)
        if g is not None:
            return g.value
        raise AttributeError(name)

    def __setattr__(self, name, value):
        registry = object.__getattribute__(self, "registry")
        c = registry.counters.get(name)
        if c is not None:
            c.value = value
            return
        g = registry.gauges.get(name)
        if g is not None:
            g.value = value
            return
        raise AttributeError(name)

    def count_relay(self, mtype_byte: int, nbytes: int) -> None:
        """Attribute ``nbytes`` of sent wire traffic to its frame type.

        Called from the one send choke point (``_Peer.send``) so the
        per-msgtype ``relay.bytes.*`` counters and the exhaustive
        ``_RELAY_ACCOUNTING`` family table together account for every
        byte the node puts on the wire (plus the 4-byte length prefix,
        matching ``bytes_sent``).  Unknown bytes are ignored rather
        than raising — the send path must never die on a frame the
        decoder would reject anyway.
        """
        registry = object.__getattribute__(self, "registry")
        counters = object.__getattribute__(self, "relay_counters")
        c = counters.get(mtype_byte)
        if c is None:
            name = _RELAY_COUNTER_NAME.get(mtype_byte)
            if name is None:
                return
            c = registry.counter(name)
            counters[mtype_byte] = c
        c.value += nbytes

    def relay_bytes(self) -> dict:
        """{family: bytes_sent} over ``_RELAY_ACCOUNTING`` families."""
        counters = object.__getattribute__(self, "relay_counters")
        out: dict = {}
        for mtype_byte, c in counters.items():
            family = _RELAY_ACCOUNTING[MsgType(mtype_byte)]
            out[family] = out.get(family, 0) + c.value
        return out

    @property
    def hashes_per_sec(self) -> float:
        return self.hashes_done / self.mine_elapsed_s if self.mine_elapsed_s else 0.0

    def propagation_summary(self) -> dict:
        """{median_ms, p95_ms, samples} over the rolling delay window."""
        delays = sorted(self.propagation_delays_s)
        if not delays:
            return {"median_ms": None, "p95_ms": None, "samples": 0}
        return {
            "median_ms": round(1e3 * delays[len(delays) // 2], 3),
            "p95_ms": round(1e3 * delays[min(len(delays) - 1, int(0.95 * len(delays)))], 3),
            "samples": len(delays),
        }


@dataclasses.dataclass
class _PendingCompact:
    """A compact block whose missing transactions are in flight."""

    header: "BlockHeader"
    txs: list  # block-order slots; None where a tx is still missing
    want: dict  # index -> advertised txid (what GETBLOCKTXN asked for)
    sent_ts: float  # original sender's timestamp (propagation telemetry)
    #: When the GETBLOCKTXN round trip was issued (monotonic).  The
    #: supervision loop abandons reconstructions older than the sync
    #: stall deadline and recovers the block via locator sync instead of
    #: waiting on the FIFO cap — a peer that never answers must not be
    #: able to delay a pushed block by squatting the pending slot.
    asked_at: float = 0.0


@dataclasses.dataclass
class _SnapshotFetch:
    """One in-flight snapshot download (manifest, then chunk ranges).
    Everything verifies incrementally: the manifest's anchor block
    before any chunk is asked for, each chunk's digest the moment it
    lands.  Purely in-RAM — a crash mid-transfer loses it and the next
    boot simply starts over (the normal-resume recovery contract)."""

    peer: "_Peer"
    asked_at: float
    manifest: "chain_snapshot.Manifest | None" = None
    chunks: list = dataclasses.field(default_factory=list)


class _Peer:
    def __init__(
        self,
        writer: asyncio.StreamWriter,
        label: str,
        metrics: NodeMetrics | None = None,
    ):
        self.writer = writer
        self.label = label
        self.metrics = metrics
        self.synced_once = False
        #: The peer's advertised listening address (peername host + HELLO
        #: listen port), once the handshake ran; None for non-listening
        #: tooling clients.  Keys the discovery loop's "already connected"
        #: check and is what GETADDR replies share.
        self.addr: tuple[str, int] | None = None
        #: The address WE dialed to reach this peer, if outbound.  May be
        #: an alias of ``addr`` (hostname vs peername IP) — the discovery
        #: loop treats both as connected so it never dials a live peer
        #: again under a different spelling.
        self.dial_addr: tuple[str, int] | None = None
        #: The tip height the peer advertised in its HELLO — the bar our
        #: own chain must reach before the initial mempool sync is worth
        #: requesting (see ``mempool_requested``).
        self.hello_height = 0
        #: One-shot: the initial mempool sync for this peer has been
        #: requested.  It is deferred until our chain has caught up to the
        #: peer's advertised height — pool admission checks affordability
        #: against OUR tip, so asking for transactions while our chain is
        #: still behind would refuse perfectly valid spends of balances we
        #: haven't learned yet.  (Keyed on the advertised height, not on
        #: one peer's batch quiescing: with several peers serving the same
        #: blocks, a duplicate batch quiesces early while the ledger is
        #: still behind.)
        self.mempool_requested = False
        #: (fee, txid) of the last mempool-sync tx received from this peer;
        #: must strictly advance in key order or the sync stops (hostile
        #: responders can't loop us).
        self.mempool_cursor: tuple[int, bytes] | None = None
        #: When a GETMEMPOOL page request to this peer went out and no
        #: MEMPOOL reply has landed yet (None = nothing outstanding).
        #: The supervision loop treats an aged entry as a stalled sync.
        self.mempool_inflight_since: float | None = None
        #: True once the peer's HELLO carried a nonzero instance nonce —
        #: a real node, not a one-shot tooling client.  Only nodes are
        #: eligible targets for sync failover (a wallet ignores
        #: GETBLOCKS).
        self.is_node = False
        #: Sync-priority demerits: one per supervised fetch this peer
        #: stalled.  A demotion, never a ban — the peer keeps its
        #: connection and its gossip, it just sorts last when the node
        #: picks who to re-ask (supervision.py's design note).
        self.sync_demerits = 0
        # --- Set-reconciliation relay state (round 23, Erlay analog) ---
        #: Pairwise short-id salt, derived from the two instance nonces
        #: at HELLO (node/reconcile.py ``pair_salt``).  None until the
        #: handshake ran, or for tooling clients (nonce 0) — a peer
        #: without a salt is simply flooded to, like every peer before
        #: this round.
        self.recon_salt: bytes | None = None
        #: short_id -> txid of txs queued for the NEXT reconciliation
        #: round on this link instead of being flooded (insertion
        #: ordered; bounded by RECON_PENDING_MAX with flood as the
        #: overflow valve).
        self.recon_pending: dict[int, bytes] = {}
        #: Responder side: the short_id -> txid set we sketched in our
        #: last SKETCH reply, held until the initiator's RECONCILDIFF
        #: closes the round (serves their diff / GETTX fetches from it).
        self.recon_window: dict[int, bytes] = {}
        #: True when recon_window was sketched for a FULL-pool round
        #: (initial mempool sync) — failure must not flood whole pools.
        self.recon_window_full = False
        #: The serve station: short_id -> txid of the last CLOSED round
        #: (either role), kept so the peer's deferred GETTX resolves
        #: without a pool scan.  Replaced whole each close — never
        #: merged — so it cannot grow past one round's size.
        self.recon_served: dict[int, bytes] = {}
        #: Initiator side: the short_id -> txid set frozen into the
        #: round in flight, and whether that round is a full-pool sync.
        self.recon_round: dict[int, bytes] = {}
        self.recon_round_full = False
        #: When our REQRECON went out and no usable SKETCH has landed
        #: (None = no round in flight).  Aged entries count as failed
        #: rounds — a silent responder must not wedge the plane.
        self.recon_inflight_since: float | None = None
        #: Short ids the peer announced in RECONCILDIFF that we have not
        #: yet received as TX pushes; the next tick GETTXes leftovers.
        self.recon_expect: set[int] = set()
        #: Consecutive failed/stalled rounds; reaching
        #: RECON_DEMOTE_FAILURES demotes the peer to flood until
        #: ``recon_demoted_until`` passes.
        self.recon_failures = 0
        self.recon_demoted_until = 0.0
        #: One-shot: the initial mempool sync should run as a full-pool
        #: reconciliation round on the next tick (set where the classic
        #: path would have sent GETMEMPOOL).
        self.recon_full_pending = False
        #: Remote host (peername IP), for per-HOST accounting such as the
        #: ADDR budget — per-connection state would reset on reconnect.
        self.host: str | None = (
            writer.get_extra_info("peername") or (None,)
        )[0]
        #: Per-peer multi-class admission budget (node/governor.py),
        #: assigned by the session once the governor is known.
        self.budget = None

    async def send(self, payload: bytes) -> None:
        await protocol.write_frame(self.writer, payload)
        # Counted after write+drain: failed sends don't inflate the total.
        # Known slack: a send cancelled between write and drain (guarded
        # timeout) may still be flushed by the transport and reach the
        # peer uncounted — the figure is "completed send calls", a slight
        # UNDERcount under peer stalls, never an overcount.
        if self.metrics is not None:
            self.metrics.bytes_sent += len(payload) + 4
            if payload:
                # Per-msgtype relay-byte attribution (round 23): same
                # choke point, same +4 framing overhead as bytes_sent.
                self.metrics.count_relay(payload[0], len(payload) + 4)


class Node:
    """One blockchain node: chain + mempool + p2p + (optionally) a miner."""

    def __init__(
        self,
        config: NodeConfig,
        miner: Miner | None = None,
        store: ChainStore | None = None,
        transport: Transport | None = None,
        rng: random.Random | None = None,
    ):
        self.config = config
        #: The network/clock seam (node/transport.py).  Default = real
        #: sockets + system clocks, byte-identical to the historical
        #: behavior; the simulator (node/netsim.py) injects in-memory
        #: links under a virtual clock so a thousand of these run
        #: deterministically in one process.
        self.transport = transport if transport is not None else SOCKET_TRANSPORT
        self.clock = self.transport.clock
        #: Telemetry plane (node/telemetry.py): counters, gauges, and
        #: per-stage latency histograms, reading time ONLY through the
        #: transport clock — wall time live, virtual time under the
        #: simulator.  Recording is observer-only by contract: the
        #: determinism pair (tests/test_telemetry.py) pins that a
        #: simulated run's trace digest is byte-identical with the
        #: plane enabled and disabled.
        from p1_tpu.node.telemetry import MetricsRegistry, NodeLogAdapter

        self.telemetry = MetricsRegistry(
            clock=self.clock.monotonic, enabled=config.telemetry
        )
        #: Hot-path instrumentation, pre-resolved: the block pipeline
        #: dispatches thousands of frames a second, and the generic
        #: ``registry.span()`` (dict lookup + context-manager + span
        #: allocation per region) measurably taxes it — the stage spans
        #: below use ``_tel_clock`` stamps + cached histogram refs
        #: instead (~half the cost; benchmarks/telemetry_overhead.py is
        #: the receipt).  ``_tel_clock is None`` IS the disabled check.
        if self.telemetry.enabled:
            self._tel_clock = self.clock.monotonic
            self._h_frame = self.telemetry.histogram("stage.frame_s")
            self._h_admission = self.telemetry.histogram(
                "stage.admission_s"
            )
            self._h_validate = self.telemetry.histogram("stage.validate_s")
            self._h_store = self.telemetry.histogram("stage.store_s")
            self._h_relay = self.telemetry.histogram("stage.relay_s")
            self._h_query = self.telemetry.histogram("query.request_s")
        else:
            self._tel_clock = None
        #: Deterministic 1-in-8 sampler for the PER-FRAME micro stages
        #: (frame decode, admission): they run for every frame at
        #: microsecond durations, so full recording would tax the hot
        #: path for distributions that a uniform sample captures
        #: identically.  The block stages (validate/store/relay) and
        #: query latency record every event.  A counter, not an RNG —
        #: sampling must not perturb simulated determinism.
        self._tel_tick = 0
        #: Identity-carrying logger: every record is prefixed with this
        #: node's host:port, so multi-node processes (`p1 net`, the
        #: simulator, netharness) stop interleaving anonymously.
        self.log = NodeLogAdapter(log, self._log_ident)
        #: Node-local RNG.  None (production) draws identity from the
        #: OS; a seeded instance (config.rng_seed, or injected directly)
        #: makes the node's identity AND its supervision jitter a pure
        #: function of the seed — the reproducibility contract simulated
        #: runs assert byte-for-byte.
        if rng is None and config.rng_seed is not None:
            rng = random.Random(config.rng_seed)
        self._rng = rng
        if rng is not None:
            nonce = rng.getrandbits(64) | 1
            tag = f"m-{rng.getrandbits(32):08x}"
        else:
            import secrets

            nonce = secrets.randbits(64) | 1
            tag = f"m-{secrets.token_hex(4)}"
        #: Random per-process id carried in HELLO: dialing an address that
        #: answers with OUR nonce means we dialed ourselves (an address
        #: book can legitimately learn our own address from peers) — the
        #: connection is dropped and the address forgotten.
        self.instance_nonce = nonce  # never 0 (= client)
        #: Coinbase identity: distinct per node unless pinned by config, so
        #: concurrent miners assemble *different* candidate blocks and the
        #: fork-choice machinery is actually exercised at network level.
        self.miner_id = config.miner_id or tag
        self.chain = Chain(config.difficulty, retarget=config.retarget_rule())
        if config.snapshot_interval > 0:
            self.chain.checkpoint_interval = config.snapshot_interval
        #: Snapshot plane (chain/snapshot.py, round 12).  The node's
        #: trust posture: VALIDATED until a snapshot boot, ASSUMED from
        #: snapshot adoption until the background revalidation either
        #: reproduces the snapshot's state root (flip to VALIDATED) or
        #: catches it lying (quarantine + fall back to genesis IBD —
        #: also VALIDATED, of the honest chain built so far).
        self.validation_state = VALIDATED
        self._snap_fetch: _SnapshotFetch | None = None
        #: Manifest of the ADOPTED snapshot (None unless ASSUMED) and
        #: the host that served it (divergence blames it).
        self._snap_meta = None
        self._snap_source: str | None = None
        #: Background revalidation: a second, genesis-anchored Chain
        #: replaying the real history through the batched-signature
        #: lane while the assumed chain serves.  None unless ASSUMED.
        self._bg_chain: Chain | None = None
        self._bg_last_staller: _Peer | None = None
        #: Served-snapshot cache: ((height, block hash), (manifest
        #: payload, chunk payloads), bytes) for the latest checkpoint —
        #: rebuilt lazily when the checkpoint moves, charged to the
        #: memory gauge.
        self._snapshot_cache = None
        #: Incremental snapshot residue (round 20, chain/snapshot.py
        #: ``build_records_incremental``): the per-account builder state
        #: from the LAST checkpoint build, plus the dirty accounts whose
        #: changes postdate that build's checkpoint — together they make
        #: the next build O(accounts touched), never O(accounts).
        self._snapshot_inc = None
        self._snapshot_dirty: set[str] = set()
        #: Version-bits activation engine (round 20,
        #: chain/versionbits.py): in-place protocol evolution by miner
        #: signal.  Empty deployment table (the default) mines the
        #: legacy ``version=1`` byte-identically to every prior round.
        self.versionbits = VersionBits(
            tuple(Deployment(*d) for d in config.deployments),
            window=config.vb_window,
            threshold=config.vb_threshold,
        )
        #: Optional version-bits gate for the reconciliation relay: when
        #: the deployment table carries a "txrecon" row, recon rounds are
        #: initiated only once it reaches ACTIVE — the mixed-version
        #: mesh upgrades link by link as miners signal, flood remaining
        #: the shared dialect throughout (PR 17's evolution contract).
        self._recon_deployment = next(
            (d for d in self.versionbits.deployments if d.name == "txrecon"),
            None,
        )
        #: Round-robin cursor over outbound recon-active peers — one
        #: reconciliation initiation per tick, not a thundering herd.
        self._recon_rotate = 0
        #: Same shape for the GETTX chase: one link's announced-but-
        #: undelivered ids fetched per tick (the dedup pacing).
        self._recon_chase_rotate = 0
        #: txid -> monotonic arrival stamp for accepted txs (bounded,
        #: insertion-ordered).  Pure observation for the propagation
        #: budget (scenarios read it to compute relay p95); never feeds
        #: back into relay decisions.
        self.tx_seen_at: dict[bytes, float] = {}
        #: Name of the maintenance operation currently running, or None.
        #: One at a time: rebase/prune/compact each assume the store
        #: segment set is not shifting under them, and serializing here
        #: is cheaper than making them mutually crash-consistent.
        self._maintenance_busy: str | None = None
        #: Verify-once signature cache (core/sigcache.py): ONE instance
        #: shared by this node's mempool admission and its chain's block
        #: validation, so a transfer verified at relay/admission connects
        #: (and mines) without re-paying the Ed25519 backend — and the
        #: hit/miss telemetry in ``status()["validation"]`` is this
        #: node's own, not the process default's.
        self.sig_cache = SignatureCache()
        self.chain.sig_cache = self.sig_cache
        if config.verify_workers > 0:
            # Explicit pin only: the lazy default (env, else cpu_count)
            # must survive multi-node test processes where the conftest
            # knob pinned workers=1 for determinism.
            keys.set_verify_workers(config.verify_workers)
        elif config.pipeline_workers > 0:
            # Staged pipeline sizing: --pipeline-workers N without an
            # explicit verify pin sizes the Ed25519 verify pool too —
            # the validate lane's parallelism lives INSIDE the verify
            # pool (one lane thread fanning a preverify batch), so the
            # two knobs default together.
            keys.set_verify_workers(config.pipeline_workers)
        if config.sig_backend != "auto":
            # Same explicit-pin discipline for the signature backend
            # (core/keys.py ladder): "auto" must not clobber another
            # node's pin in multi-node test processes.
            keys.set_sig_backend(config.sig_backend)
        # balance_of is a bound-late lambda (not a bound method) so the
        # store-resume path in start(), which REPLACES self.chain, keeps
        # the pool pointed at the live chain's ledger.  The chain tag is
        # safe to bind eagerly: it is a pure function of the chain
        # parameters (difficulty + retarget rule), which a resume cannot
        # change (start() refuses mismatched stores).
        self.mempool = Mempool(
            balance_of=lambda acct: self.chain.balance(acct),
            nonce_of=lambda acct: self.chain.nonce(acct),
            chain_tag=self.chain.genesis.block_hash(),
            sig_cache=self.sig_cache,
            # The transport clock, so admission stamps / TTL ages ride
            # virtual time under the simulator like every node deadline.
            clock=self.clock.monotonic,
        )
        self.metrics = NodeMetrics(registry=self.telemetry)
        #: The wallet push plane (node/subscriptions.py): watch-filter
        #: subscriptions pushed at every block connect, reading the
        #: chain through a late-bound getter because start()'s resume
        #: paths and live re-basing REPLACE self.chain.
        from p1_tpu.node.subscriptions import (
            ChainSubSource,
            SubscriptionManager,
        )

        self.subscriptions = SubscriptionManager(
            ChainSubSource(lambda: self.chain),
            clock=self.clock.monotonic,
            registry=self.telemetry,
        )
        #: ``store`` is injectable (tests pass a fault-injecting
        #: ``chain/testing.py`` FaultStore); by default the config path
        #: decides persistence.
        if store is not None:
            self.store = store
        elif config.store_path:
            # Layout sniffing (chain/segstore.py): an existing
            # segmented store reopens segmented regardless of flags;
            # --store-segment-mb / --prune opt a fresh or single-file
            # store into the segmented layout (single-file upgrades
            # losslessly on acquire).  Spelled as a conditional over
            # the two constructors — the analysis plane's attribute
            # binder unifies them to the ChainStore base, keeping the
            # store-blocking call chains provable.
            from p1_tpu.chain.segstore import (
                DEFAULT_SEGMENT_BYTES,
                SegmentedStore,
                is_segmented,
            )

            seg_bytes = config.store_segment_bytes
            if config.prune_keep_blocks > 0 and seg_bytes == 0:
                seg_bytes = DEFAULT_SEGMENT_BYTES
            self.store = (
                SegmentedStore(
                    config.store_path,
                    segment_bytes=seg_bytes or DEFAULT_SEGMENT_BYTES,
                )
                if seg_bytes > 0 or is_segmented(config.store_path)
                else ChainStore(config.store_path)
            )
        else:
            self.store = None
        #: Storage degradation state (the disk analog of sync-stall
        #: failover): a failed append/fsync flips the node into a
        #: degraded SERVE-ONLY mode — it stops accepting/persisting new
        #: blocks and stops mining, but keeps answering headers/blocks/
        #: proof/account queries from the chain it already holds — while
        #: ``_store_recovery_loop`` retries the disk under the same
        #: jittered-backoff policy the sync supervisor uses.  Blocks
        #: accepted in the failing instant wait in ``_store_pending`` so
        #: recovery persists them in order before new ones.
        self._store_degraded = False
        self._store_last_error: str | None = None
        self._store_pending: list[Block] = []
        self._store_sup = RequestSupervisor(
            stall_timeout_s=1.0,  # unused: only the backoff math is
            attempts_max=1 << 30,  # borrowed, and retries never exhaust
            backoff_base_s=config.sync_backoff_base_s,
            backoff_max_s=config.sync_backoff_max_s,
            clock=self.clock.monotonic,
            rng=self._rng,
        )
        #: Set when a store failure should end the process instead of
        #: degrading (``--store-degraded-exit``); the CLI watches it.
        self.store_fatal = asyncio.Event()
        #: Overload resilience (node/governor.py): per-peer admission
        #: budgets, the write-queue caps, and the SHED state machine over
        #: the accounted memory gauge (``_memory_gauge``) — the third leg
        #: of the degradation triad after sync-stall and disk-fault
        #: handling.
        self.governor = ResourceGovernor(
            watermark_bytes=config.mem_watermark_bytes,
            admission=config.admission_control,
            clock=self.clock.monotonic,
        )
        #: Staged block pipeline (node/pipeline.py, round 19): the
        #: validate and store lanes every CPU/IO-heavy stage routes
        #: through.  workers=0 (default) executes inline — scheduling
        #: byte-identical to the historical dispatch-everything-inline
        #: node; workers>=1 moves signature pre-verification and the
        #: whole fsync chain onto worker threads.  Lane depth/bytes feed
        #: ``_memory_gauge`` so queue growth back-pressures at the
        #: governor's front door; worker deaths respawn and count into
        #: the task-crash lineage below.
        self.pipeline = NodePipeline(
            workers=config.pipeline_workers,
            on_respawn=self._worker_respawned,
        )
        if miner is not None:
            self.miner = miner
        else:
            kwargs = {"batch": config.batch} if config.batch else {}
            from p1_tpu.hashx import get_backend

            self.miner = Miner(
                backend=get_backend(config.backend, **kwargs), chunk=config.chunk
            )
        self._peers: dict[asyncio.StreamWriter, _Peer] = {}
        #: Supervision of the node-wide locator catch-up job: ONE
        #: progress deadline over "is this chain still advancing toward
        #: what peers advertised", targeting whichever peer was asked
        #: last.  Any accepted block is progress (the serving peer does
        #: not matter — catch-up converges on the same chain from
        #: anyone), so an honest-slow peer that keeps landing batches
        #: never trips it; a peer that answers PINGs but starves the
        #: sync does, and the locator fails over (_check_block_sync).
        self._sync = RequestSupervisor(
            stall_timeout_s=config.sync_stall_timeout_s or 10.0,
            attempts_max=config.sync_attempts_max,
            backoff_base_s=config.sync_backoff_base_s,
            backoff_max_s=config.sync_backoff_max_s,
            clock=self.clock.monotonic,
            rng=self._rng,
        )
        #: Supervision of the background revalidation fetch (its own
        #: supervisor: the assumed chain's tip sync and the history
        #: replay are independent jobs with independent stall blame).
        self._bg_sup = RequestSupervisor(
            stall_timeout_s=config.sync_stall_timeout_s or 10.0,
            attempts_max=config.sync_attempts_max,
            backoff_base_s=config.sync_backoff_base_s,
            backoff_max_s=config.sync_backoff_max_s,
            clock=self.clock.monotonic,
            rng=self._rng,
        )
        #: Set when a batch-synced block (gossip=False — locator sync,
        #: orphan backfill) moved our tip: the catch-up path never
        #: re-gossips individual blocks (a 500-block IBD must not push
        #: 500 frames at every peer), so when the episode QUIESCES the
        #: node announces its final tip once instead.  Without this, a
        #: block only propagates as far as nodes that could connect it
        #: directly: the first peer that needed a backfill becomes a
        #: gossip dead end, and after a partition heals, mesh regions
        #: with no direct link across the old cut never converge — found
        #: by the 1000-node partition-heal simulation (node/netsim.py),
        #: invisible at the 7-node scale real sockets allowed.  It is
        #: Bitcoin's post-IBD tip announcement, one flag's worth.
        self._announce_tip = False
        #: Discovery dials in flight (dedup against the next tick).
        self._dialing: set[tuple[str, int]] = set()
        #: Misbehavior scoring: host -> recent violation times / ban expiry.
        self._violations: dict[str, collections.deque] = {}
        self._banned_until: dict[str, float] = {}
        #: (block hash, announcing peer) -> partially reconstructed compact
        #: block (see ``_handle_cblock``); FIFO-capped.  Keyed per PEER so
        #: a front-runner pushing a tampered txid list for a real block
        #: cannot squat the hash — an honest peer's announcement of the
        #: same block reconstructs independently — and so a BLOCKTXN reply
        #: only ever resolves the request sent to that same peer.
        self._pending_cblocks: collections.OrderedDict[
            tuple[bytes, _Peer], _PendingCompact
        ] = collections.OrderedDict()
        #: Address book, two buckets: ``_known_addrs`` ("new") is seeded
        #: from config and fed by ADDR gossip, FIFO-bounded — hostile
        #: gossip churns only here; ``_tried_addrs`` holds addresses a
        #: completed handshake verified, bounded separately, and gossip
        #: can never evict them (the eclipse-resistance split).  The
        #: discovery loop (``target_peers`` > 0) dials tried first.
        #: Neither contains our own address knowingly — a self-dial is
        #: detected by nonce and the address dropped.
        self._known_addrs: collections.OrderedDict[
            tuple[str, int], float
        ] = collections.OrderedDict(
            (addr, 0.0) for addr in config.peer_addrs()
        )
        self._tried_addrs: collections.OrderedDict[
            tuple[str, int], float
        ] = collections.OrderedDict()
        #: Per-HOST unsolicited-ADDR token buckets: host -> [tokens,
        #: last_refill].  Keyed like the misbehavior tracking (not per
        #: connection — a reconnect must not refresh the budget, or ~16
        #: quick reconnects flush the whole gossip book) and bounded the
        #: same way against address-cycling attackers.
        self._addr_budgets: dict[str, list[float]] = {}
        #: Pool mutation count at the last persisted checkpoint, and the
        #: in-flight checkpoint writer task (stop() drains it before the
        #: final synchronous save — see _checkpoint_mempool).
        self._mempool_saved_at = 0
        self._mempool_io: asyncio.Task | None = None
        self._server: asyncio.Server | None = None
        self._tasks: list[asyncio.Task] = []
        #: Live session/background tasks in CREATION order (a dict used
        #: as an ordered set: ``stop()`` iterates it, and reproducible
        #: simulated runs need reproducible teardown order — a plain
        #: set's id()-based iteration was a trace-divergence source).
        self._sessions: dict[asyncio.Task, None] = {}
        #: Inbound sessions still inside the HELLO exchange (MAX_HANDSHAKING).
        self._handshaking = 0
        self._abort = None  # threading.Event of the in-flight search
        self._mine_task: asyncio.Task | None = None
        self._post_seal: asyncio.Task | None = None  # shielded seal handling
        self._running = False
        self.port: int | None = None  # bound listen port (after start)

    # -- lifecycle -------------------------------------------------------

    def _log_ident(self) -> str:
        """This node's log attribution: the configured host plus the
        BOUND port once the listener is up (before that, the configured
        one — 0 for ephemeral test nodes, which still disambiguates by
        host under the simulator)."""
        return f"{self.config.host}:{self.port if self.port else self.config.port}"

    def _untrack_session(self, task) -> None:
        """Done-callback for fire-and-forget session tasks (dials, sync
        failovers): untrack, and OBSERVE a crash.  Without the
        ``exception()`` read, a task dying of a bug is silent until the
        GC maybe logs "exception was never retrieved" — the round-3
        dead-recovery-loop failure shape the lost-task lint rule pins.
        Expected connection-layer failures are handled inside the tasks
        themselves; anything surfacing HERE is a programming error, so
        it is logged loudly and counted."""
        self._sessions.pop(task, None)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self.metrics.task_crashes += 1
            self.log.error("session task %r died: %r", task.get_name(), exc)

    def _worker_respawned(self, stage: str) -> None:
        """Pipeline lane worker died and was respawned (node/pipeline.py
        supervision) — same observability contract as the task
        supervisor above: the crash is COUNTED, never silent."""
        self.metrics.worker_respawns += 1
        self.log.warning("%s pipeline worker died; respawned", stage)

    def _addr_book_path(self):
        return (
            Path(f"{self.config.store_path}.addrs")
            if self.config.store_path
            else None
        )

    def _mempool_path(self):
        return (
            Path(f"{self.config.store_path}.mempool")
            if self.config.store_path
            else None
        )

    def _snapshot_path(self):
        """The snapshot sidecar next to the store: present exactly while
        the node is (or crashed while) in the ASSUMED state — a resume
        that finds it boots from the snapshot again and restarts the
        background revalidation; the flip deletes it."""
        if self.store is not None:
            return Path(f"{self.store.path}.snapshot")
        if self.config.store_path:
            return Path(f"{self.config.store_path}.snapshot")
        return None

    def _load_mempool(self) -> None:
        """Resume the pending pool (Bitcoin's mempool.dat analog): every
        record re-passes full admission against the freshly loaded chain,
        so anything the downtime invalidated is dropped, and restored
        ages keep the TTL clock honest across the restart."""
        from p1_tpu.mempool import load_mempool

        path = self._mempool_path()
        if path is None or not path.exists():
            return
        restored, dropped = load_mempool(self.mempool, path)
        if restored or dropped:
            self.log.info(
                "mempool resumed: %d restored, %d dropped on revalidation",
                restored,
                dropped,
            )

    def _save_mempool(self) -> None:
        """Synchronous save (shutdown path — nothing left to stall)."""
        from p1_tpu.mempool import save_mempool

        path = self._mempool_path()
        if path is None:
            return
        try:
            save_mempool(self.mempool, path)
            self._mempool_saved_at = self.mempool.mutations
        except OSError as e:
            self.log.warning("could not persist mempool %s: %s", path, e)

    async def _checkpoint_mempool(self) -> None:
        """Periodic crash checkpoint: skipped when the pool is unchanged
        since the last save; the encoding AND atomic write both run in a
        worker thread — a near-capacity pool (~tens of MB) must not
        stall frame reads, ping deadlines, or mining for the duration.
        The snapshot itself is taken on the event loop, where all pool
        mutation happens, so it is internally consistent (transactions
        are frozen dataclasses — safe to serialize off-thread).  The
        worker future is exposed as ``_mempool_io`` so ``stop()`` can
        wait it out: cancelling this coroutine does NOT stop the thread,
        and a stale checkpoint landing after the shutdown save would
        silently roll the file back."""
        from p1_tpu.mempool import dump_mempool, write_mempool_file

        path = self._mempool_path()
        if path is None or self.mempool.mutations == self._mempool_saved_at:
            return
        mutations = self.mempool.mutations
        rows = self.mempool.snapshot()
        # Store-lane seam, not a bare to_thread: the checkpoint rides
        # the same writer lane as every other persistence chain (append
        # order with respect to block writes is preserved when staged),
        # and ``offload=True`` keeps it off-loop at workers=0 exactly as
        # the historical to_thread call did.
        self._mempool_io = asyncio.create_task(
            self.pipeline.run_store(
                lambda: write_mempool_file(dump_mempool(rows), path),
                offload=True,
            )
        )
        try:
            await self._mempool_io
            self._mempool_saved_at = mutations
        except (OSError, WorkerCrash) as e:
            self.log.warning("could not persist mempool %s: %s", path, e)
        finally:
            self._mempool_io = None

    def _load_addr_book(self) -> None:
        """Resume discovery state: a restarting node re-joins the network
        it knew instead of depending on its seed peers being alive."""
        path = self._addr_book_path()
        if path is None or not path.exists():
            return
        try:
            entries = json.loads(path.read_text())
        except (ValueError, OSError) as e:
            self.log.warning("ignoring unreadable address book %s: %s", path, e)
            return
        # Two formats: the current {"tried": [...], "new": [...]} split
        # and the legacy flat list (loaded as "new" — a restart earns
        # tried status afresh through real handshakes).
        if isinstance(entries, dict):
            tried_rows = entries.get("tried", [])
            new_rows = entries.get("new", [])
            if not isinstance(tried_rows, list) or not isinstance(
                new_rows, list
            ):
                self.log.warning("ignoring malformed address book %s", path)
                return
        elif isinstance(entries, list):
            tried_rows, new_rows = [], entries
        else:
            # Parsable-but-wrong content is just as corrupt as unparsable
            # bytes — the book is a cache, never worth failing startup.
            self.log.warning("ignoring malformed address book %s", path)
            return

        def _rows(rows, limit):
            for entry in rows[:limit]:
                try:
                    host, port = entry
                    # Mirror the ADDR wire rules (protocol.encode_addr):
                    # a row the codec would refuse must not enter the
                    # book, or every later GETADDR reply dies on our own
                    # encode.
                    if (
                        isinstance(host, str)
                        and 0 < len(host.encode("utf-8")) <= 255
                        and 0 < int(port) <= 0xFFFF
                    ):
                        yield (host, int(port))
                except (TypeError, ValueError):
                    continue  # one bad row must not poison the rest

        for addr in _rows(tried_rows, MAX_TRIED_ADDRS):
            self._tried_addrs.setdefault(addr, 0.0)
        for addr in _rows(new_rows, MAX_KNOWN_ADDRS):
            if addr not in self._tried_addrs:
                self._known_addrs.setdefault(addr, 0.0)

    def _save_addr_book(self) -> None:
        path = self._addr_book_path()
        if path is None:
            return
        try:
            tmp = path.with_suffix(".addrs.tmp")
            tmp.write_text(
                json.dumps(
                    {
                        "tried": [list(a) for a in self._tried_addrs],
                        "new": [list(a) for a in self._known_addrs],
                    }
                )
            )
            tmp.replace(path)  # atomic: never a torn book
        except OSError as e:
            self.log.warning("could not persist address book %s: %s", path, e)

    def _try_snapshot_resume(self) -> bool:
        """Resume a node that crashed (or stopped) in the ASSUMED state:
        the ``.snapshot`` sidecar holds the verified snapshot, the store
        holds only snapshot-descendant records.  Returns True when the
        assumed chain was rebuilt (the caller skips the genesis resume).

        Robustness cases, all exercised by the chaos plane:

        - flip completed but crashed before the sidecar unlink: the
          store's first record connects from genesis — the sidecar is
          stale; delete it and take the normal resume;
        - sidecar unreadable/corrupt (bit-rot while down): quarantine it
          and fall through to the normal resume with ``orphans_ok`` (the
          snapshot-descendant records park as orphans and ordinary IBD
          rebuilds from peers) — never a refused boot;
        - the normal case: rebuild the assumed chain from the sidecar,
          replay the store's post-snapshot records onto it, restart the
          background revalidation."""
        snap_path = self._snapshot_path()
        if snap_path is None or not snap_path.exists():
            return False
        ghash = self.chain.genesis.block_hash()
        first = self.store.first_header()
        if first is not None and (
            first.block_hash() == ghash or first.prev_hash == ghash
        ):
            # The flip's store rewrite landed; only the unlink is owed.
            self.log.info("stale snapshot sidecar after a completed flip — removing")
            snap_path.unlink()
            return False
        try:
            snap = chain_snapshot.load_snapshot(snap_path)
        except (OSError, SnapshotError) as e:
            self.log.error(
                "snapshot sidecar unreadable (%s) — quarantining; booting "
                "via ordinary IBD",
                e,
            )
            try:
                os.replace(
                    snap_path, snap_path.with_name(snap_path.name + ".quarantine")
                )
            except OSError:
                pass
            self._orphans_ok_boot = True
            return False
        chain = Chain.from_snapshot(
            self.config.difficulty, snap, retarget=self.config.retarget_rule()
        )
        chain.sig_cache = self.sig_cache
        if self.config.snapshot_interval > 0:
            chain.checkpoint_interval = self.config.snapshot_interval
        anchor = snap.block_hash
        for block in self.store.load_blocks():
            if block.block_hash() == anchor:
                continue
            # The node's own flocked log of blocks it validated while
            # ASSUMED: the same trusted-resume contract as the genesis
            # path (contextual rules + ledger still run).
            chain.add_block(block, trusted=True)
        self.chain = chain
        self.validation_state = ASSUMED
        self._snap_meta = snap.manifest
        if self.config.body_cache_blocks > 0:
            chain.body_source = self.store
        self.log.warning(
            "resumed in ASSUMED state from snapshot at height %d "
            "(tip %d) — background revalidation restarting",
            snap.height,
            chain.height,
        )
        return True

    def _prunebase_path(self):
        if self.config.store_path is None:
            return None
        return Path(f"{self.config.store_path}.prunebase")

    def _try_prunebase_resume(self) -> bool:
        """Resume a PRUNED node: history below the prune floor is gone
        from disk by policy, so the genesis resume cannot reconnect the
        surviving records — the ``.prunebase`` sidecar (this node's OWN
        snapshot of its validated state, written before each prune)
        anchors the chain at the prune base instead and the surviving
        segments replay on top.  Unlike a peer-served snapshot this
        boots VALIDATED: the state is ours, persisted under the writer
        lock, the same trust the trusted resume extends to the log.  A
        missing/corrupt sidecar degrades to ordinary IBD with
        ``orphans_ok`` (safe, just slower) — never a refused boot."""
        if getattr(self.store, "pruned_below", 0) <= 0:
            return False
        base_path = self._prunebase_path()
        if base_path is None or not base_path.exists():
            self._orphans_ok_boot = True
            return False
        try:
            snap = chain_snapshot.load_snapshot(base_path)
        except (OSError, SnapshotError) as e:
            self.log.error(
                "prune-base sidecar unreadable (%s) — quarantining; "
                "booting via ordinary IBD",
                e,
            )
            try:
                os.replace(
                    base_path,
                    base_path.with_name(base_path.name + ".quarantine"),
                )
            except OSError:
                pass
            self._orphans_ok_boot = True
            return False
        chain = Chain.from_snapshot(
            self.config.difficulty, snap, retarget=self.config.retarget_rule()
        )
        chain.assumed = False  # our own validated state, not a peer claim
        chain.sig_cache = self.sig_cache
        if self.config.snapshot_interval > 0:
            chain.checkpoint_interval = self.config.snapshot_interval
        anchor = snap.block_hash
        for block in self.store.iter_blocks():
            if block.block_hash() == anchor:
                continue
            chain.add_block(block, trusted=True)
        chain.prune_floor = self.store.pruned_below
        self.chain = chain
        if self.config.body_cache_blocks > 0:
            chain.body_source = self.store
        self.log.info(
            "resumed pruned chain base=%d tip=%d (bodies below %d "
            "discarded; headers in the segment plane)",
            snap.height,
            chain.height,
            self.store.pruned_below,
        )
        return True

    async def start(self) -> None:
        self._load_addr_book()
        self._orphans_ok_boot = False
        if self.store is not None:
            # Hold the store's writer lock for the node's whole lifetime
            # (not just from the first append): a second node on the same
            # store, or a compaction while we run, must fail loudly.
            self.store.acquire()
            if self._try_snapshot_resume():
                self._load_mempool()
                return await self._start_services()
            if self._try_prunebase_resume():
                self._load_mempool()
                return await self._start_services()
            body_cache = self.config.body_cache_blocks
            if body_cache > 0:
                # Memory-bounded resume: never materialize the whole
                # block list — the store streams records through
                # load_chain's eviction loop, so peak RSS is bounded by
                # the keep window.  The difficulty pre-check reads just
                # the first record's header.
                blocks = None
                held_difficulty = self.store.first_difficulty()
            else:
                blocks = self.store.load_blocks()
                held_difficulty = (
                    blocks[0].header.difficulty if blocks else None
                )
            if (
                held_difficulty is not None
                and held_difficulty != self.config.difficulty
            ):
                # Restarting with a different --difficulty would silently
                # reject every persisted record and interleave a second,
                # incompatible chain behind them.  Release the writer lock
                # before raising: an in-process retry with the corrected
                # difficulty must not find its own leaked flock (ADVICE r3).
                self.store.close()
                raise RuntimeError(
                    f"store {self.store.path} holds a difficulty-"
                    f"{held_difficulty} chain; node configured "
                    f"for {self.config.difficulty}"
                )
            # load_chain already routes every record through full add_block
            # validation, and keeps persisted side branches alive (store.py)
            # — adopt it wholesale instead of re-validating main_chain only.
            # Its none-connected guard (a store from a chain with different
            # parameters) surfaces as ValueError; close the store before
            # re-raising so a corrected in-process retry doesn't find its
            # own stale flock.
            try:
                self.chain = self.store.load_chain(
                    self.config.difficulty,
                    blocks,
                    retarget=self.config.retarget_rule(),
                    # Our own flocked log of blocks we already validated:
                    # fast resume by default (store.py's trust argument).
                    # A revalidation (trusted=False) runs through the
                    # batched signature fast lane against THIS node's
                    # verify-once cache.
                    trusted=not self.config.revalidate_store,
                    body_cache=body_cache,
                    sig_cache=self.sig_cache,
                    # A heal that quarantined records may have cut the
                    # log loose from genesis; the survivors park as
                    # orphans and the ordinary locator sync backfills
                    # the gap — refusing to boot here bricked crash
                    # recovery (found by the chaos sweep, node/chaos.py).
                    # Same relaxation when a quarantined SNAPSHOT sidecar
                    # left the store holding snapshot-descendant records
                    # with no genesis linkage (_try_snapshot_resume).
                    orphans_ok=self.store.healed["quarantined_records"] > 0
                    or self._orphans_ok_boot,
                )
            except ValueError as e:
                self.store.close()
                raise RuntimeError(str(e)) from e
            if self.config.snapshot_interval > 0:
                # The resume built a fresh Chain; re-apply the
                # checkpoint-cadence override (roots recorded at the
                # default cadence during the load stay — they are valid
                # commitments, just differently spaced).
                self.chain.checkpoint_interval = self.config.snapshot_interval
            if body_cache > 0:
                # Keep evicting as the chain grows past resume (the
                # governor loop sweeps; the source survives the resume).
                self.chain.body_source = self.store
            if self.chain.height:
                self.log.info(
                    "resumed chain height=%d tip=%s",
                    self.chain.height,
                    self.chain.tip_hash.hex()[:16],
                )
            # After the chain: admission validates against the ledger.
            self._load_mempool()
        await self._start_services()

    async def _start_services(self) -> None:
        """Everything after chain/mempool resume: the listener and the
        background loops — one tail shared by the genesis and snapshot
        resume paths."""
        self._running = True
        # The resume paths grew the chain with nobody subscribed; the
        # push plane promises events from NOW, not a replay of boot.
        self.subscriptions.reset_cursor()
        self._server = await self.transport.listen(
            self._on_inbound, self.config.host, self.config.port
        )
        self.port = self._server.port
        self.log.info("listening on %s:%d", self.config.host, self.port)
        for host, port in self.config.peer_addrs():
            self._tasks.append(asyncio.create_task(self._dial_loop(host, port)))
        if self.config.target_peers > 0:
            self._tasks.append(asyncio.create_task(self._discovery_loop()))
        if self.config.mempool_ttl_s > 0 or self.store is not None:
            # TTL expiry and/or the crash checkpoint: a persistent node
            # with expiry disabled still checkpoints its pool.
            self._tasks.append(asyncio.create_task(self._housekeeping_loop()))
        if self.config.sync_stall_timeout_s > 0:
            # Request supervision: progress deadlines + failover for
            # every multi-round fetch (0 disables, e.g. single-peer
            # tooling rigs that want no background re-requests).
            self._tasks.append(asyncio.create_task(self._supervision_loop()))
        # Set-reconciliation heartbeat (round 23), spawned UNCONDITIONALLY:
        # round initiation is gated per tick (operator switch + "txrecon"
        # deployment state), but the tick's bookkeeping half — aging out
        # silent rounds and GETTX-chasing diff ids a recon-ON peer
        # announced to us — must run even on a recon-off node, or a
        # straggler that answers sketches could book announced ids and
        # never fetch them.
        self._tasks.append(asyncio.create_task(self._recon_loop()))
        if (
            self.config.mem_watermark_bytes > 0
            or self.config.body_cache_blocks > 0
        ):
            # Overload governor tick: gauge observation (SHED
            # hysteresis) and the body-eviction sweep.  Skipped when
            # neither feature is configured — admission control and the
            # write-queue caps are inline and need no loop.
            self._tasks.append(asyncio.create_task(self._governor_loop()))
        if self.validation_state == ASSUMED:
            # A (re)boot in the ASSUMED state owes the network a finished
            # revalidation: restart the background lane immediately.
            self._bg_start()
        if self.config.mine:
            self.start_mining()

    async def stop(self) -> None:
        self._running = False
        # Stop the miner FIRST: stop_mining awaits the shielded final-block
        # handling while peer sessions are still alive, so a block sealed in
        # the last instant reaches peers before any connection is torn down.
        await self.stop_mining()
        # Cancel inbound session handlers along with our own tasks BEFORE
        # waiting on the server: Python 3.12's Server.wait_closed() blocks
        # until every connection handler returns, and handlers sit in
        # read_frame() indefinitely.
        pending = [*self._tasks, *self._sessions]
        for task in pending:
            task.cancel()
        await asyncio.gather(*pending, return_exceptions=True)
        self._tasks.clear()
        self._sessions.clear()
        for writer in list(self._peers):
            writer.close()
        self._peers.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._save_addr_book()
        if self._mempool_io is not None:
            # A cancelled housekeeping task cannot cancel its worker
            # THREAD: let any in-flight checkpoint write finish before
            # the authoritative shutdown save, or the stale file could
            # land second and roll back every admission since.
            await asyncio.gather(self._mempool_io, return_exceptions=True)
        # Drain the pipeline lanes: any store-lane job already submitted
        # (appends, prune sidecars, checkpoint writes) completes before
        # the synchronous shutdown writes below — stop() must never race
        # its own store worker for the flock.
        self.pipeline.drain_and_close()
        self._save_mempool()
        if self.store is not None:
            if self._store_pending:
                # Last chance: the disk may have recovered since the
                # failure; anything still unwritable is re-fetchable
                # from peers on the next start.
                self._store_flush()
            self.store.close()

    def start_mining(self) -> None:
        """Start the mining loop on a running node (idempotent)."""
        if self._running and self._mine_task is None:
            self._mine_task = asyncio.create_task(self._mine_loop())
            self._tasks.append(self._mine_task)

    async def stop_mining(self) -> None:
        """Stop the mining loop but keep the node gossiping (tests/CLI)."""
        if self._mine_task is not None:
            self._mine_task.cancel()
            self._abort_inflight_search()
            try:
                await self._mine_task
            except asyncio.CancelledError:
                pass
            except Exception:
                # A mine loop that already died of its own exception re-raises
                # it here; stop()/stop_mining() must still run the rest of
                # teardown (sessions, server socket, store).
                self.log.exception("mine task ended with error")
            if self._mine_task in self._tasks:
                self._tasks.remove(self._mine_task)
            self._mine_task = None
        # A block sealed in the final instant enters the chain and reaches
        # peers only once the shielded _post_seal task runs to completion —
        # cancelling the mine loop does not cancel it, and awaiting it here
        # is what guarantees callers observe a fully-propagated stop.
        await self._await_post_seal()

    async def _await_post_seal(self) -> None:
        if self._post_seal is not None:
            results = await asyncio.gather(
                self._post_seal, return_exceptions=True
            )
            self._post_seal = None
            for r in results:
                if isinstance(r, BaseException) and not isinstance(
                    r, asyncio.CancelledError
                ):
                    # Nothing else can surface a failure on this path (the
                    # mine loop is already gone) — don't lose it.
                    self.log.error("post-seal block handling failed: %r", r)

    # -- storage durability (degraded serve-only mode) --------------------

    async def _store_append(self, blocks) -> None:
        """Persist freshly accepted blocks — the STORE stage.  The
        append + fsync chain runs on the pipeline's store-writer lane
        (inline when staging is off), so the event loop never waits on
        the disk when a worker is configured; failure handling stays on
        the loop (``_store_fail`` touches asyncio state).

        A failing disk (ENOSPC, EIO, fsync error) degrades the NODE
        instead of unwinding the connection handler that happened to
        deliver the block — the fault is the disk's, never the peer's,
        and dropping the session would punish a healthy peer and
        reconnect-loop forever against the same full disk."""
        if self.store is None:
            return
        self._store_pending.extend(blocks)
        if not self._store_degraded:
            if await self._store_flush_staged(
                nbytes=sum(len(b.serialize()) for b in blocks)
            ):
                await self._maybe_prune()

    async def _store_flush_staged(self, nbytes: int = 0) -> bool:
        """Drain pending records via the store lane; True = caught up."""
        exc = await self.pipeline.run_store(self._store_flush_io, nbytes=nbytes)
        if exc is not None:
            self._store_fail(exc)
            return False
        return True

    def _store_flush(self) -> bool:
        """Synchronous drain (the shutdown path — stop() runs after the
        pipeline lanes closed, so the final flush is direct by design)."""
        exc = self._store_flush_io()
        if exc is not None:
            self._store_fail(exc)
            return False
        return True

    def _store_flush_io(self) -> OSError | None:
        """Pure IO: write every pending record in order; returns the
        failure instead of raising (it runs on the store lane, and the
        degradation machinery — supervisor spawns, asyncio.Event — must
        only ever run on the loop).  Reads of ``_store_pending`` and
        the chain index are GIL-atomic; the lane is single-threaded, so
        two drains never interleave."""
        while self._store_pending:
            block = self._store_pending[0]
            try:
                # The height hint feeds the segmented store's manifest
                # (height spans -> segments, what pruning consults); a
                # record the chain no longer indexes appends heightless.
                entry = self.chain._index.get(block.block_hash())
                self.store.append(
                    block, height=entry.height if entry else None
                )
            except OSError as e:
                return e
            self._store_pending.pop(0)
        return None

    async def _maybe_prune(self) -> None:
        """Pruned mode (round 18): discard body segments wholly below
        the prune floor — the older of (tip - prune_keep_blocks) and
        the latest snapshot-checkpoint height, so a pruned node can
        always still serve its newest snapshot's rollback window.
        Cheap when there is nothing to do (one pass over the manifest
        rows).  The decision and the ledger-state capture run ON-loop
        (they read live chain structures the loop mutates); the sidecar
        write + unlinks run on the store lane."""
        keep = self.config.prune_keep_blocks
        if keep <= 0 or self.store is None:
            return
        if getattr(self.store, "prune_below", None) is None:
            return  # single-file layout: nothing to discard per segment
        interval = self.chain.checkpoint_interval
        checkpoint = (self.chain.height // interval) * interval
        floor = min(self.chain.height - keep, checkpoint)
        if floor <= self.chain.prune_floor:
            return
        await self._prune_now(floor)

    async def _prune_now(self, floor: int) -> int:
        """The prune executor shared by the automatic policy above and
        the explicit ``online_prune`` maintenance command: durable
        prune-base sidecar first, then the segment unlinks, on the
        store lane.  Returns segments removed (0 when nothing qualifies
        or the store failed — a failure flips degraded mode)."""
        if not self.store.prunable_segments(floor):
            return 0
        # The prune-base sidecar FIRST, durably: our own validated
        # state at the latest checkpoint is what the next boot
        # anchors on once the history below it stops existing.
        state = self.chain.snapshot_state()
        if state is None:
            return 0
        s_height, s_block, balances, nonces, _root = state
        manifest, chunks = chain_snapshot.build_records(
            s_height, s_block, balances, nonces
        )
        result = await self.pipeline.run_store(
            self._prune_io, manifest, chunks, floor
        )
        if isinstance(result, OSError):
            self._store_fail(result)
            return 0
        if result:
            self.metrics.store_segments_pruned += result
            self.chain.prune_floor = self.store.pruned_below
            self.log.info(
                "pruned %d body segment(s) below height %d "
                "(headers retained)",
                result,
                self.store.pruned_below,
            )
        return result

    def _prune_io(self, manifest, chunks, floor) -> int | OSError:
        """Store-lane half of pruning: durable prune-base sidecar, then
        the segment unlinks.  Returns segments removed, or the failure."""
        try:
            base_path = self._prunebase_path()
            tmp = base_path.with_name(f"{base_path.name}.{os.getpid()}")
            chain_snapshot.write_snapshot(tmp, manifest, chunks)
            os.replace(tmp, base_path)
            fsync_dir(base_path.parent)
            return self.store.prune_below(floor)
        except OSError as e:
            return e

    # -- the always-on maintenance plane (round 20) -----------------------

    async def _maintain(self, command) -> dict:
        """Execute one maintenance command (the GETMAINTAIN wire frame,
        driven by `p1 maintain`).  Refusals are ANSWERS — ``{"ok":
        false, "error": ...}`` — never dropped sessions or protocol
        violations: the whole point of the plane is that operating on a
        live node must not cost it connectivity.  One operation at a
        time (``_maintenance_busy``): rebase/prune/compact each assume
        the segment set is not shifting under them."""
        if not isinstance(command, dict):
            return {"ok": False, "error": "maintenance command must be an object"}
        op = command.get("op")
        if op == "status":
            return {"ok": True, **self.maintenance_report()}
        if op not in ("rebase", "prune", "compact"):
            return {"ok": False, "error": f"unknown maintenance op {op!r}"}
        if self._maintenance_busy is not None:
            return {
                "ok": False,
                "error": f"maintenance busy: {self._maintenance_busy}",
            }
        if self.validation_state != VALIDATED:
            return {
                "ok": False,
                "error": "chain is assumed: maintenance waits for revalidation",
            }
        if self._store_degraded:
            return {
                "ok": False,
                "error": "store degraded: maintenance needs a healthy disk",
            }
        keep = command.get("keep", self.chain.checkpoint_interval)
        if not isinstance(keep, int) or isinstance(keep, bool) or keep < 0:
            return {"ok": False, "error": "keep must be a non-negative integer"}
        self._maintenance_busy = op
        try:
            if op == "rebase":
                return await self.rebase(keep)
            if op == "prune":
                return await self.online_prune(keep)
            return await self.online_compact()
        finally:
            self._maintenance_busy = None

    async def rebase(self, keep: int) -> dict:
        """Live re-basing, leg (a) of the zero-downtime plane: advance
        the chain's base to the newest checkpoint at least ``keep``
        blocks below the tip WITHOUT restarting.  Ordering is the crash
        contract: the store half runs first and durably (seal the
        active segment, spill ``.hdrx``/``.sdx`` sidecars for every
        sealed segment, off-loop on the store lane), so by the time the
        in-RAM index forgets the deep history it is already servable
        and bootable from the sidecar planes — a kill between the two
        halves reboots as an un-rebased node with spare sidecars."""
        chain = self.chain
        interval = chain.checkpoint_interval
        target = ((chain.height - keep) // interval) * interval
        if target <= chain.base_height:
            return {
                "ok": False,
                "error": (
                    f"nothing to rebase: target {target} at or below "
                    f"base {chain.base_height}"
                ),
            }
        if target not in chain.state_checkpoints:
            return {
                "ok": False,
                "error": f"no state checkpoint at height {target}",
            }
        t0 = self.clock.monotonic()
        if self.store is not None and hasattr(self.store, "ensure_sidecars"):

            def _spill():
                try:
                    self.store.roll_segment()
                    return self.store.ensure_sidecars()
                except OSError as e:
                    return e

            spilled = await self.pipeline.run_store(_spill, offload=True)
            if isinstance(spilled, OSError):
                self._store_fail(spilled)
                return {"ok": False, "error": f"sidecar spill failed: {spilled}"}
        stats = chain.rebase(target)
        self.metrics.rebases += 1
        self.log.info(
            "rebased live: base %d -> %d, dropped %d block(s), "
            "freed ~%d bytes",
            stats["old_base"],
            stats["new_base"],
            stats["dropped_blocks"],
            stats["freed_bytes"],
        )
        return {
            "ok": True,
            "duration_s": round(self.clock.monotonic() - t0, 6),
            **stats,
        }

    async def online_prune(self, keep: int) -> dict:
        """Online pruning, half of leg (c): discard body segments
        wholly below min(tip - keep, latest checkpoint) on the LIVE
        node — the explicit-command twin of the automatic
        ``_maybe_prune`` policy, sharing its executor (and therefore
        its prune-base durability ordering) exactly."""
        if self.store is None or getattr(self.store, "prune_below", None) is None:
            return {"ok": False, "error": "online prune needs a segmented store"}
        chain = self.chain
        checkpoint = (
            chain.height // chain.checkpoint_interval
        ) * chain.checkpoint_interval
        floor = min(chain.height - keep, checkpoint)
        t0 = self.clock.monotonic()
        if floor <= chain.prune_floor:
            pruned = 0
        else:
            pruned = await self._prune_now(floor)
            if self._store_degraded:
                return {
                    "ok": False,
                    "error": self._store_last_error or "store failed during prune",
                }
        self.metrics.online_prunes += 1
        return {
            "ok": True,
            "segments_pruned": pruned,
            "floor": chain.prune_floor,
            "duration_s": round(self.clock.monotonic() - t0, 6),
        }

    async def online_compact(self) -> dict:
        """Online compaction, the other half of leg (c): rewrite dirty
        sealed segments without their dead (off-main-chain) records
        while the node keeps mining and serving.  Split exactly like
        pruning: the expensive half (read sealed bytes, build verified
        replacements under tmp names) runs off-loop on the store lane
        and never touches a live file; each swap then commits ON-loop
        between awaits — rename + span-table fixup as one synchronous
        step, so no reader can observe a half-swapped segment.  The
        drop set is only ever hashes this chain POSITIVELY indexes off
        its main chain — unknown records are kept (chain/tooling.py's
        rule), so online compaction can never widen data loss."""
        store = self.store
        if store is None or getattr(store, "plan_compaction", None) is None:
            return {"ok": False, "error": "online compact needs a segmented store"}
        chain = self.chain
        drop = {
            bhash
            for bhash, entry in chain._index.items()
            if chain.main_hash_at(entry.height) != bhash
        }
        t0 = self.clock.monotonic()

        def _plan():
            try:
                return store.plan_compaction(drop)
            except OSError as e:
                return e

        plans = await self.pipeline.run_store(_plan, offload=True)
        if isinstance(plans, OSError):
            self._store_fail(plans)
            return {"ok": False, "error": f"compaction planning failed: {plans}"}
        committed: list[int] = []
        dropped = 0
        for i, plan in enumerate(plans):
            try:
                n = store.commit_compacted_segment(plan)
            except OSError as e:
                # A failed swap degrades the store like any other write
                # fault; unswapped replacements are stale the moment it
                # recovers, so discard them all.
                store.discard_compaction(plans[i:])
                self._store_fail(e)
                return {"ok": False, "error": f"compaction commit failed: {e}"}
            if n:
                committed.append(plan["seg_id"])
                dropped += n
        if committed:

            def _refresh():
                try:
                    store.refresh_sidecars(committed)
                    store.flush_manifest()
                except OSError as e:
                    return e

            refreshed = await self.pipeline.run_store(_refresh, offload=True)
            if isinstance(refreshed, OSError):
                self._store_fail(refreshed)
                return {
                    "ok": False,
                    "error": f"post-compaction refresh failed: {refreshed}",
                }
            self.metrics.segments_compacted += len(committed)
            self.metrics.compaction_records_dropped += dropped
            self.log.info(
                "compacted %d segment(s) online, dropped %d dead record(s)",
                len(committed),
                dropped,
            )
        self.metrics.online_compactions += 1
        return {
            "ok": True,
            "segments_compacted": len(committed),
            "records_dropped": dropped,
            "duration_s": round(self.clock.monotonic() - t0, 6),
        }

    def maintenance_report(self) -> dict:
        """The maintenance plane's JSON surface — ``status()`` embeds
        it, and ``{"op": "status"}`` over GETMAINTAIN serves it alone.
        Fixed key set (tests/test_telemetry.py pins status keys)."""
        return {
            "busy": self._maintenance_busy,
            "rebases": self.metrics.rebases,
            "online_prunes": self.metrics.online_prunes,
            "online_compactions": self.metrics.online_compactions,
            "segments_compacted": self.metrics.segments_compacted,
            "compaction_records_dropped": (
                self.metrics.compaction_records_dropped
            ),
            "snapshot_incremental_builds": (
                self.metrics.snapshot_incremental_builds
            ),
            "snapshot_chunks_reused": self.metrics.snapshot_chunks_reused,
            "base_height": self.chain.base_height,
            "versionbits": {
                "window": self.versionbits.window,
                "threshold": self.versionbits.threshold,
                "deployments": self.versionbits.states_report(self.chain),
            },
        }

    async def _store_sync_staged(self) -> None:
        """Guarded batch-close fsync via the store lane (the BLOCKS
        resync path)."""
        if self.store is None or self._store_degraded:
            return
        exc = await self.pipeline.run_store(self._store_sync_io)
        if exc is not None:
            self._store_fail(exc)

    def _store_sync_io(self) -> OSError | None:
        try:
            self.store.sync()
        except OSError as e:
            return e
        return None

    def _store_fail(self, exc: OSError) -> None:
        self.metrics.store_errors += 1
        self._store_last_error = f"{type(exc).__name__}: {exc}"
        if self._store_degraded:
            return
        self._store_degraded = True
        self.log.error(
            "store write failed (%s) — entering degraded serve-only mode "
            "(%d records pending)",
            exc,
            len(self._store_pending),
        )
        # Stop chasing blocks we would only refuse: the in-flight sync
        # episode ends, the in-flight nonce search aborts (the mining
        # loop pauses itself while degraded).
        self._sync.idle()
        self._abort_inflight_search()
        if self.config.store_degraded_exit:
            # Escape hatch for operators who prefer a supervisor restart
            # to a degraded node: signal the CLI runner and stand down.
            self.log.critical(
                "store failed and --store-degraded-exit is set — "
                "signaling shutdown"
            )
            self.store_fatal.set()
            return
        if self._running:
            self._spawn_store_recovery()

    def _spawn_store_recovery(self) -> None:
        task = asyncio.create_task(self._store_recovery_loop())
        self._sessions[task] = None
        task.add_done_callback(self._store_recovery_done)

    def _store_recovery_done(self, task: asyncio.Task) -> None:
        """A recovery task that dies while the node is still degraded
        would strand it serve-only forever — ``_store_fail`` early-returns
        once degraded, so nothing else ever respawns the loop.  Surface
        the wreck and restart; the loop's own backoff (first await) keeps
        a persistent crash from spinning."""
        self._sessions.pop(task, None)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        self.log.error("store recovery loop died: %r", exc)
        if self._running and self._store_degraded:
            self._spawn_store_recovery()

    async def _store_recovery_loop(self) -> None:
        """Retry the store under the RequestSupervisor backoff policy
        (jittered exponential, same knobs as sync failover) until writes
        succeed again, then leave degraded mode and backfill: the blocks
        refused at the door are re-fetched from peers via an ordinary
        locator sync — nothing was acknowledged, so nothing is owed."""
        sup = self._store_sup
        while self._running and self._store_degraded:
            retry_delay = sup.record_stall()
            self.telemetry.observe("store.retry_backoff_s", retry_delay)
            await asyncio.sleep(retry_delay)
            if not (self._running and self._store_degraded):
                return
            self.metrics.store_retries += 1
            # Disk retries ride the store lane too — the recovery probe
            # must not re-inline the very fsync chain the lane absorbed.
            if not await self._store_flush_staged():
                continue  # still failing: _store_fail counted it, back off
            # Prove durability, not just a buffered write.  (With an
            # empty pending list this can pass while the disk is
            # still full — the next real append re-degrades, which
            # is self-correcting.)
            exc = await self.pipeline.run_store(self._store_sync_io)
            if exc is not None:
                self.metrics.store_errors += 1
                self._store_last_error = f"{type(exc).__name__}: {exc}"
                continue
            self._store_degraded = False
            self._store_last_error = None
            self.metrics.store_recoveries += 1
            sup.attempts = 0
            sup.idle()
            self.log.warning(
                "store recovered — leaving degraded mode, backfilling "
                "blocks refused meanwhile"
            )
            await self.request_sync()
            return

    # -- untrusted snapshot sync (chain/snapshot.py, round 12) ------------

    def _snapshot_records(self):
        """(manifest payload, chunk payloads) for the latest checkpoint
        height, built lazily and cached until the checkpoint moves (or
        reorgs).  None while ASSUMED — a node must never relay state it
        has not itself validated — or when the chain is too short to
        hold a checkpoint."""
        if self.validation_state != VALIDATED:
            return None
        chain = self.chain
        height = (chain.height // chain.checkpoint_interval) * (
            chain.checkpoint_interval
        )
        if height <= chain.base_height:
            return None
        key = (height, chain.main_hash_at(height))
        if self._snapshot_cache is not None and self._snapshot_cache[0] == key:
            return self._snapshot_cache[1]
        state = chain.snapshot_state()
        if state is None:
            return None
        h, block, balances, nonces, root = state
        # Incremental build (round 20): re-encode only the accounts the
        # ledger touched since the LAST build — the pending residue
        # (accounts whose changes postdated the previous checkpoint)
        # plus everything applied/undone since.  A superset of the true
        # diff is always safe; missing an account would serve a stale
        # chunk, so the manifest root is cross-checked against the
        # chain's own checkpoint commitment below.
        self._snapshot_dirty |= chain.collect_dirty_accounts()
        manifest_payload, chunks, inc, reused = (
            chain_snapshot.build_records_incremental(
                self._snapshot_inc,
                h,
                block,
                balances,
                nonces,
                self._snapshot_dirty,
            )
        )
        built_root = chain_snapshot.parse_manifest(manifest_payload).state_root
        if built_root != root:
            # The incremental path disagreeing with the validated
            # checkpoint root means the dirty set missed an account —
            # a bug, but one that must cost a full rebuild, never a
            # lying snapshot on the wire.
            self.log.error(
                "incremental snapshot root mismatch at height %d; "
                "falling back to full rebuild",
                h,
            )
            manifest_payload, chunks, inc, reused = (
                chain_snapshot.build_records_incremental(
                    None, h, block, balances, nonces, set()
                )
            )
        self.metrics.snapshot_incremental_builds += 1
        self.metrics.snapshot_chunks_reused += reused
        self._snapshot_inc = inc
        # Accounts touched by blocks BEYOND this checkpoint were just
        # consumed from the dirty set but are not reflected in the
        # published state — they must stay dirty for the next build.
        try:
            self._snapshot_dirty = self._dirty_beyond(h)
        except OSError:
            # A body refetch failing (degraded store) only costs the
            # residue: the next build runs cold but correct.
            self._snapshot_inc = None
            self._snapshot_dirty = set()
        size = (
            len(manifest_payload)
            + sum(len(c) for c in chunks)
            # The builder residue is retained state too: charge its
            # dominant parts (entry payloads + leaf hashes) to the same
            # gauge the served-snapshot cache rides.
            + sum(len(e) for e in inc.entries.values())
            + 32 * len(inc.leaves)
        )
        self._snapshot_cache = (key, (manifest_payload, chunks), size)
        return manifest_payload, chunks

    def _dirty_beyond(self, height: int) -> set[str]:
        """Accounts touched by main-chain blocks ABOVE ``height`` — the
        part of the ledger's dirty set a snapshot anchored AT ``height``
        does not capture.  O(blocks past the checkpoint), normally under
        one checkpoint interval; bodies may refetch from the store."""
        from p1_tpu.chain.statedelta import block_accounts

        chain = self.chain
        out: set[str] = set()
        for hh in range(height + 1, chain.height + 1):
            bh = chain.main_hash_at(hh)
            if bh is None:
                continue
            out |= block_accounts(chain._block_at(bh))
        return out

    async def _request_snapshot(self, peer: _Peer) -> None:
        """Start a snapshot download from ``peer`` (manifest first).
        Supervised like every other multi-round fetch: stalls demote and
        fail over (``_check_snapshot_fetch``)."""
        self._snap_fetch = _SnapshotFetch(
            peer=peer, asked_at=self.clock.monotonic()
        )
        self.metrics.snapshot_fetches += 1
        self.log.info("requesting state snapshot from %s", peer.label)
        await self._send_guarded(peer, protocol.encode_getsnapshot(0, 0))

    def _validate_snapshot_manifest(self, manifest) -> None:
        """Cheap-to-check gates BEFORE any chunk round trips: the anchor
        block must carry real work (full stateless validation — the same
        PoW-before-state discipline as compact-block handling), and the
        claimed shape must be bounded.  Raises SnapshotError /
        ValidationError."""
        if manifest.height < 1:
            raise SnapshotError("snapshot at genesis height")
        if len(manifest.chunk_digests) > SNAPSHOT_MAX_CHUNKS:
            raise SnapshotError(
                f"{len(manifest.chunk_digests)} chunks exceeds the "
                f"{SNAPSHOT_MAX_CHUNKS} cap"
            )
        # On a retargeting chain the contextual difficulty of a deep
        # block is unknowable without the history (the very thing a
        # snapshot skips) — check PoW at the CLAIMED difficulty, like
        # orphan parking; the background revalidation re-checks it
        # contextually.  Difficulty 0 would pass vacuously.
        claimed = (
            manifest.block.header.difficulty
            if self.chain.retarget is not None
            else self.config.difficulty
        )
        if claimed < 1:
            raise SnapshotError("workless snapshot anchor")
        from p1_tpu.chain.validate import check_block

        check_block(
            manifest.block,
            claimed,
            chain_tag=self.chain.genesis.block_hash(),
            sig_cache=self.sig_cache,
        )

    async def _snapshot_fetch_failed(
        self, peer: _Peer, reason: str, score: bool
    ) -> None:
        """Abandon the in-flight snapshot download.  ``score=True`` for
        integrity violations (bad digests, bad manifest — forgery,
        scorable); stalls stay unscored (slowness is not a violation).
        Either way the fetch fails over: another peer's snapshot if one
        qualifies, else ordinary genesis IBD — the node always has a
        trust-free path forward."""
        self._snap_fetch = None
        self.log.warning("snapshot fetch from %s failed: %s", peer.label, reason)
        if peer.writer in self._peers:
            peer.sync_demerits += 1
            self.metrics.sync_demotions += 1
        if score and peer.host:
            self._record_violation(peer.host)
        other = self._pick_sync_peer(exclude=peer)
        if other is not None and self._snapshot_worthwhile(other):
            await self._request_snapshot(other)
        elif other is not None:
            await self._request_blocks(other)
        elif peer.writer in self._peers:
            # Last peer standing: IBD from it validates everything, so
            # no trust is extended by falling back to ordinary sync.
            await self._request_blocks(peer)

    def _snapshot_worthwhile(self, peer: _Peer) -> bool:
        """Would a snapshot from ``peer`` beat ordinary IBD right now?"""
        return (
            self.config.snapshot_sync
            and self.config.sync_stall_timeout_s > 0
            and peer.is_node
            and self.validation_state == VALIDATED
            and self._snap_fetch is None
            and self._bg_chain is None
            and self.chain.height == 0
            and peer.hello_height - self.chain.height
            >= max(1, self.config.snapshot_min_lead)
        )

    async def _handle_snapshot(self, body, peer: _Peer) -> None:
        """One SNAPSHOT reply (manifest or chunk range) of an in-flight
        fetch.  Unsolicited frames are ignored; every byte verifies
        against the manifest as it arrives."""
        fetch = self._snap_fetch
        if fetch is None or fetch.peer is not peer:
            return
        now = self.clock.monotonic()
        if body[0] == "none":
            # The peer serves no snapshot (too short, or itself ASSUMED):
            # not a fault — fall back to ordinary sync with it.
            self._snap_fetch = None
            await self._request_blocks(peer)
            return
        if body[0] == "manifest":
            if fetch.manifest is not None:
                return  # duplicate
            try:
                manifest = chain_snapshot.parse_manifest(body[1])
                self._validate_snapshot_manifest(manifest)
            except (SnapshotError, ValidationError) as e:
                await self._snapshot_fetch_failed(
                    peer, f"bad manifest: {e}", score=True
                )
                return
            fetch.manifest = manifest
            fetch.asked_at = now
            await self._send_guarded(
                peer, protocol.encode_getsnapshot(0, SNAPSHOT_BATCH)
            )
            return
        # chunks
        if fetch.manifest is None:
            return  # chunks before the manifest: ignore
        _, start, chunks = body
        if start != len(fetch.chunks) or not chunks:
            return  # stale/duplicate range; supervision re-asks on stall
        digests = fetch.manifest.chunk_digests
        for payload in chunks:
            i = len(fetch.chunks)
            if i >= len(digests) or chain_snapshot.chunk_digest(
                payload
            ) != digests[i]:
                # Lying mid-transfer: caught on THIS chunk, before the
                # rest of the download is paid for.
                await self._snapshot_fetch_failed(
                    peer, f"chunk {i} fails its manifest digest", score=True
                )
                return
            fetch.chunks.append(payload)
        fetch.asked_at = now
        if len(fetch.chunks) < len(digests):
            await self._send_guarded(
                peer,
                protocol.encode_getsnapshot(len(fetch.chunks), SNAPSHOT_BATCH),
            )
            return
        try:
            snap = chain_snapshot.assemble(fetch.manifest, fetch.chunks)
        except SnapshotError as e:
            await self._snapshot_fetch_failed(peer, str(e), score=True)
            return
        self._snap_fetch = None
        await self._adopt_snapshot(snap, fetch.chunks, peer)

    async def _adopt_snapshot(self, snap, chunk_payloads, peer: _Peer) -> None:
        """Enter the ASSUMED state: swap the serving chain for one
        anchored on the verified snapshot, persist the sidecar, start
        the background revalidation, and catch up to the serving peer's
        tip.  The node serves balance/header/proof queries from this
        instant — that is the whole point — while trusting nothing
        beyond what it can still detect and undo."""
        if self.validation_state != VALIDATED or self._bg_chain is not None:
            return
        if snap.height <= self.chain.height:
            # An ordinary sync outran the download while it was in
            # flight — the validated chain is already past the snapshot,
            # so there is nothing left worth assuming.
            return
        chain = Chain.from_snapshot(
            self.config.difficulty, snap, retarget=self.config.retarget_rule()
        )
        chain.sig_cache = self.sig_cache
        if self.config.snapshot_interval > 0:
            chain.checkpoint_interval = self.config.snapshot_interval
        self.chain = chain
        self.validation_state = ASSUMED
        self._snap_meta = snap.manifest
        self._snap_source = peer.host
        self._abort_inflight_search()  # mining pauses while ASSUMED
        self.log.warning(
            "booted from snapshot: height=%d root=%s from %s — ASSUMED "
            "state, serving immediately; background revalidation starting",
            snap.height,
            snap.state_root.hex()[:16],
            peer.label,
        )
        snap_path = self._snapshot_path()
        if snap_path is not None:
            try:
                # Store lane: sidecar IO (write + fsync) is writer work.
                await self.pipeline.run_store(
                    chain_snapshot.write_snapshot,
                    snap_path,
                    chain_snapshot.encode_manifest(snap.manifest),
                    chunk_payloads,
                )
            except OSError as e:
                self.log.warning("could not persist snapshot sidecar: %s", e)
        # Reset the store onto the assumed layout (anchor + descendants):
        # any genesis-connected records an outrun ordinary sync already
        # persisted would otherwise leave a mixed log the resume cannot
        # interpret.  The history they held is re-fetched (and properly
        # revalidated) by the background lane anyway.
        await self.pipeline.run_store(self._rewrite_store, chain)
        if self.store is not None and self.config.body_cache_blocks > 0:
            chain.body_source = self.store
        self._bg_start()
        await self._request_blocks(peer)

    def _bg_start(self) -> None:
        """Arm the background revalidation: a second, genesis-anchored
        chain that replays the REAL history through the batched
        validation lane (PR 5) while the assumed chain serves.  The
        fetch itself is driven by ``_check_bg_sync`` ticks and the
        BLOCKS routing in ``_dispatch``."""
        if self._bg_chain is not None or self._snap_meta is None:
            return
        chain = Chain(
            self.config.difficulty, retarget=self.config.retarget_rule()
        )
        chain.sig_cache = self.sig_cache
        if self.config.snapshot_interval > 0:
            chain.checkpoint_interval = self.config.snapshot_interval
        # Pin the snapshot height as an explicit checkpoint so the
        # divergence comparison reads an exact-height root regardless of
        # how the serving node's interval relates to ours.
        chain.checkpoint_extra.add(self._snap_meta.height)
        self._bg_chain = chain

    async def _bg_request(self, peer: _Peer) -> None:
        if self._bg_chain is None:
            return
        self._bg_sup.begin(peer)
        await self._send_guarded(
            peer, protocol.encode_getblocks(self._bg_chain.locator())
        )

    async def _check_bg_sync(self) -> None:
        """Supervision tick for the background revalidation fetch: kick
        it when idle, demote + fail over when the serving peer stalls —
        the same progress-buys-the-slot contract as the main sync."""
        bg = self._bg_chain
        if bg is None:
            return
        sup = self._bg_sup
        if not sup.active:
            if not sup.ready():
                return  # backoff from the last stall still arming
            if sup.exhausted():
                sup.attempts = 0  # new episode after the cooldown
            peer = self._pick_sync_peer(exclude=self._bg_last_staller)
            if peer is not None:
                await self._bg_request(peer)
            return
        staller = sup.target
        gone = staller.writer not in self._peers
        if not (gone or sup.stalled()):
            return
        self.metrics.snapshot_stalls += 1
        if not gone:
            staller.sync_demerits += 1
            self.metrics.sync_demotions += 1
            self.log.warning(
                "background revalidation stalled on %s — failing over",
                staller.label,
            )
        self._bg_last_staller = staller
        sup.record_stall()  # arms the jittered backoff; next tick re-kicks
        # The unobtainable-history rule: the snapshot came with an
        # implicit promise that its history exists.  If the replay has
        # consumed everything every connected peer advertises and still
        # sits below the snapshot height, nobody can back the claim —
        # an unbackable snapshot is treated exactly like a lying one
        # (quarantine + fall back to the validated chain).  Advertised
        # heights are handshake-stale, so this only under-triggers: a
        # peer that has since grown past the snapshot height will push
        # its blocks and the replay resumes through the normal routes.
        meta = self._snap_meta
        if meta is not None and self._bg_chain is not None:
            peer_best = max(
                (
                    p.hello_height
                    for p in self._peers.values()
                    if p.is_node
                ),
                default=0,
            )
            if peer_best < meta.height and bg.height >= peer_best:
                await self._snapshot_diverged(
                    "snapshot history unobtainable: no connected peer "
                    "advertises the snapshot height"
                )

    async def _check_snapshot_fetch(self, now: float) -> None:
        """Supervision tick for an in-flight snapshot download."""
        fetch = self._snap_fetch
        if fetch is None:
            return
        deadline = self.config.sync_stall_timeout_s
        if (
            fetch.peer.writer in self._peers
            and now - fetch.asked_at <= deadline
        ):
            return
        self.metrics.snapshot_stalls += 1
        await self._snapshot_fetch_failed(
            fetch.peer, "snapshot transfer stalled", score=False
        )

    async def _check_bg_done(self) -> None:
        """The verdict: once the background chain's main chain crosses
        the snapshot height, compare — same block hash AND same state
        root means the snapshot told the truth (flip to VALIDATED);
        anything else means it lied (quarantine + fall back)."""
        bg, meta = self._bg_chain, self._snap_meta
        if bg is None or meta is None or bg.height < meta.height:
            return
        at = bg.main_hash_at(meta.height)
        if at is None:
            return
        if at == meta.block_hash:
            root = bg.state_checkpoints.get(meta.height)
            if root == meta.state_root:
                await self._snapshot_flip()
            else:
                await self._snapshot_diverged(
                    "replayed state root does not match the snapshot's claim"
                )
        else:
            await self._snapshot_diverged(
                "snapshot anchor block is not on the fully-validated chain"
            )

    async def _snapshot_flip(self) -> None:
        """ASSUMED → VALIDATED: the replayed history reproduced the
        snapshot's state root, so the background chain (which now holds
        the full validated history) becomes the serving chain, with the
        assumed chain's post-snapshot blocks transplanted on top.  The
        store is rewritten as a full genesis-first log and the sidecar
        removed — a later restart is an ordinary resume."""
        bg, assumed = self._bg_chain, self.chain
        self._bg_chain = None
        self._bg_sup.idle()
        for h in range(assumed.base_height + 1, assumed.height + 1):
            bh = assumed.main_hash_at(h)
            if bh is None:
                break
            bg.add_block(assumed._block_at(bh))
        self.chain = bg
        self.validation_state = VALIDATED
        self.metrics.snapshot_flips += 1
        self._snap_meta = None
        self._snap_source = None
        self.log.warning(
            "background revalidation CONFIRMED the snapshot — flipped to "
            "fully-validated at height %d",
            bg.height,
        )
        # The heaviest single blocking window in the old node (~seconds
        # at 100k blocks): the genesis-first store rewrite, now absorbed
        # by the store lane.  ``bg`` is already detached from serving
        # (self.chain points at it, but nothing mutates it until this
        # coroutine resumes), so the worker reads a quiescent chain.
        await self.pipeline.run_store(self._rewrite_store, bg)
        snap_path = self._snapshot_path()
        if snap_path is not None and snap_path.exists():
            try:
                os.unlink(snap_path)
            except OSError:
                pass  # stale sidecar; the next resume detects and drops it
        if self.store is not None and self.config.body_cache_blocks > 0:
            bg.body_source = self.store
        # Mining resumes on the next loop tick (the ASSUMED gate cleared);
        # one broadcast sync mops up anything gossip dropped meanwhile,
        # and one tip announce publishes the now-fully-backed chain —
        # peers that never saw the snapshot's branch can finally
        # orphan-backfill the WHOLE history from us.
        await self.request_sync()
        await self._announce_tip_now()

    async def _snapshot_diverged(self, reason: str) -> None:
        """The snapshot LIED (or its chain lost): quarantine the sidecar,
        demote + score the serving peer, and fall back to genesis IBD on
        the fully-validated background chain — which keeps serving
        headers and every other query throughout.  Never a crash, never
        silent acceptance."""
        bg = self._bg_chain
        self._bg_chain = None
        self._bg_sup.idle()
        self.metrics.snapshot_divergences += 1
        self.metrics.snapshot_fallbacks += 1
        self.log.error(
            "snapshot DIVERGED (%s) — quarantining it, demoting the "
            "serving peer, falling back to genesis IBD",
            reason,
        )
        snap_path = self._snapshot_path()
        if snap_path is not None and snap_path.exists():
            try:
                os.replace(
                    snap_path,
                    snap_path.with_name(snap_path.name + ".quarantine"),
                )
            except OSError as e:
                self.log.warning("could not quarantine snapshot sidecar: %s", e)
        host = self._snap_source
        if host:
            self._record_violation(host)
            for p in self._peers.values():
                if p.host == host:
                    p.sync_demerits += 1
                    self.metrics.sync_demotions += 1
        self._snap_meta = None
        self._snap_source = None
        self.chain = bg
        self.validation_state = VALIDATED
        await self.pipeline.run_store(self._rewrite_store, bg)
        if self.store is not None and self.config.body_cache_blocks > 0:
            bg.body_source = self.store
        await self.request_sync()
        # The fallback chain may carry MORE work than anything our peers
        # hold (the snapshot's branch was real blocks even if its state
        # claim lied): announce it once so the mesh can weigh it — fork
        # choice, not this node, decides.
        await self._announce_tip_now()

    async def _announce_tip_now(self) -> None:
        """Push the current tip to every peer once (the validation-state
        transitions' counterpart of the post-IBD ``_announce_tip``
        flag): receivers connect it or orphan-backfill the history —
        which this node, now holding a full genesis-connected chain,
        can serve end to end."""
        if self.chain.height == 0 or not self._peers:
            return
        payload, saved = self._block_gossip_payload(self.chain.tip)
        n = await self._gossip(payload)
        if saved and n:
            self.metrics.cblocks_sent += n
            self.metrics.cblock_bytes_saved += saved * n

    def _rewrite_store(self, chain: Chain) -> None:
        """Replace the store's contents with ``chain``'s main branch
        (the flip/fallback transition out of the ASSUMED store layout,
        where records hang off a snapshot anchor instead of genesis):
        tmp + atomic replace + directory fsync, then re-acquire and
        re-index.  A failure leaves the OLD store intact — the running
        chain is authoritative either way, and the next resume's
        sidecar logic sorts out whichever layout survived."""
        if self.store is None:
            return
        from p1_tpu.chain.store import fsync_dir, save_chain

        path = self.store.path
        tmp = path.with_name(f"{path.name}.flip.{os.getpid()}")
        try:
            save_chain(chain, tmp)
            self.store.close()  # release the flock on the old inode
            os.replace(tmp, path)
            fsync_dir(path.parent)
            self.store.acquire()
            self.store.reindex_spans()
            self._store_pending.clear()
        except OSError as e:
            self.log.error(
                "store rewrite after the validation flip failed (%s) — "
                "keeping the previous layout; a restart will re-derive "
                "state from the sidecar",
                e,
            )
            try:
                if tmp.exists():
                    os.unlink(tmp)
                self.store.acquire()  # make sure the writer lock is back
            except OSError:
                pass

    # -- overload resilience (node/governor.py) ---------------------------

    def _memory_gauge(self) -> int:
        """The node's accounted memory: resident chain bodies + pending
        pool bytes + peer transport write buffers + the verify-once
        signature cache.  Deterministic and reversible (unlike OS RSS,
        which CPython's allocator rarely returns), so the SHED
        hysteresis can actually come back down when the pressure goes
        away."""
        write_buf = 0
        recon_entries = 0
        for peer in self._peers.values():
            transport = peer.writer.transport
            if transport is not None and not transport.is_closing():
                write_buf += transport.get_write_buffer_size()
            # Recon relay maps (round 23): bounded per peer, but bounded
            # is not free at MAX_PEERS x RECON_PENDING_MAX — ~36 bytes
            # per short-id->txid entry (int key + 32-byte txid).
            recon_entries += (
                len(peer.recon_pending)
                + len(peer.recon_window)
                + len(peer.recon_round)
                + len(peer.recon_served)
                + len(peer.recon_expect)
            )
        return (
            36 * recon_entries
            +
            self.chain.resident_body_bytes
            + getattr(self.mempool, "bytes_pending", 0)
            + write_buf
            + self.sig_cache.bytes_used
            # Serving-plane caches (round 9): bounded LRUs, but bounded
            # is not free — the gauge must see them or a proof/filter
            # query storm becomes untracked RAM under the watermark.
            + self.chain.proof_cache.bytes_used
            + self.chain.filter_index.bytes_used
            # Served-snapshot cache (round 12): one checkpoint's worth
            # of canonical state bytes, rebuilt per checkpoint.
            + (self._snapshot_cache[2] if self._snapshot_cache else 0)
            # Staged pipeline (round 19): bytes referenced by in-flight
            # lane jobs.  Queue growth on the validate/store lanes is
            # memory the loop has admitted but not yet retired — wiring
            # it here means back-pressure sheds at the front door
            # instead of letting worker queues balloon.
            + self.pipeline.queued_bytes
        )

    async def _governor_loop(self) -> None:
        """Gauge tick: feed the SHED state machine and run the body
        eviction sweep.  A quarter second bounds both detection latency
        under a flood and how far past the keep window the resident set
        can grow between sweeps."""
        while self._running:
            await asyncio.sleep(0.25)
            try:
                if self.config.body_cache_blocks > 0:
                    self.chain.evict_bodies(self.config.body_cache_blocks)
                if self.governor.observe(self._memory_gauge()):
                    if self.governor.shedding:
                        self.log.warning(
                            "overload: %d tracked bytes over the %d "
                            "watermark — SHED state (low-priority gossip "
                            "dropped, mining paused)",
                            self.governor.tracked_bytes,
                            self.governor.watermark_bytes,
                        )
                        # Stop burning CPU on a candidate we'd assemble
                        # under pressure; the loop pauses itself while
                        # shedding.
                        self._abort_inflight_search()
                    else:
                        self.log.warning(
                            "overload cleared: %d tracked bytes below the "
                            "low watermark — back to NORMAL",
                            self.governor.tracked_bytes,
                        )
            except Exception:
                # The governor must never die of one bad tick — it is
                # the layer that keeps overload survivable.
                self.log.exception("governor tick failed")

    # -- p2p ------------------------------------------------------------

    def _hello(self) -> bytes:
        return protocol.encode_hello(
            Hello(
                self.chain.genesis.block_hash(),
                self.chain.height,
                self.port or 0,
                self.instance_nonce,
            )
        )

    def _is_banned(self, host: str) -> bool:
        until = self._banned_until.get(host)
        if until is None:
            return False
        if self.clock.monotonic() >= until:
            del self._banned_until[host]
            return False
        return True

    def _record_violation(self, host: str) -> None:
        now = self.clock.monotonic()
        window = self._violations.setdefault(host, collections.deque())
        window.append(now)
        while window and now - window[0] > BAN_WINDOW_S:
            window.popleft()
        if len(window) >= BAN_SCORE_THRESHOLD:
            self._banned_until[host] = now + BAN_DURATION_S
            window.clear()
            self.log.warning(
                "banning %s for %.0fs after repeated protocol violations",
                host,
                BAN_DURATION_S,
            )
        # Keep the tracking itself bounded (it guards against hostile
        # input — it must not be a memory hole for address-cycling
        # attackers): prune stale entries first, oldest-arbitrary after.
        if len(self._violations) > MAX_TRACKED_HOSTS:
            cutoff = now - BAN_WINDOW_S
            self._violations = {
                h: w
                for h, w in self._violations.items()
                if w and w[-1] >= cutoff
            }
            while len(self._violations) > MAX_TRACKED_HOSTS:
                del self._violations[next(iter(self._violations))]
        if len(self._banned_until) > MAX_TRACKED_HOSTS:
            self._banned_until = {
                h: u for h, u in self._banned_until.items() if u > now
            }
            while len(self._banned_until) > MAX_TRACKED_HOSTS:
                del self._banned_until[next(iter(self._banned_until))]

    async def _on_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peername = writer.get_extra_info("peername")
        if peername and self._is_banned(peername[0]):
            # Refused before any handshake work: a banned flooder costs us
            # one accept + close, nothing more.
            writer.close()
            return
        if self._handshaking >= MAX_HANDSHAKING:
            # Accept-flood guard: sockets that haven't proven anything yet
            # may hold at most MAX_HANDSHAKING session slots between them.
            # Cost of refusal: one accept + close.
            writer.close()
            return
        task = asyncio.current_task()
        assert task is not None
        self._sessions[task] = None
        try:
            await self._peer_session(reader, writer, "in", inbound=True)
        finally:
            self._sessions.pop(task, None)

    async def _dial_loop(self, host: str, port: int) -> None:
        """Keep one outbound connection to a configured peer alive."""
        while self._running:
            try:
                reader, writer = await self.transport.connect(host, port)
            except OSError:
                await asyncio.sleep(RECONNECT_DELAY_S)
                continue
            await self._peer_session(
                reader, writer, f"out:{host}:{port}", dial_addr=(host, port)
            )
            await asyncio.sleep(RECONNECT_DELAY_S)

    async def _dial_once(self, host: str, port: int) -> None:
        """One discovery-driven connection attempt (no retry loop: the
        discovery loop re-evaluates the address book every tick, so a
        failed or rejected dial is simply superseded)."""
        try:
            try:
                reader, writer = await asyncio.wait_for(
                    self.transport.connect(host, port), timeout=5.0
                )
            except (OSError, asyncio.TimeoutError):
                # Unreachable: demote/forget (a live peer's ADDR gossip
                # re-teaches it if it comes back; a tried entry survives
                # one failure as a rumor rather than vanishing).
                self._demote_addr((host, port))
                return
            registered = await self._peer_session(
                reader, writer, f"disc:{host}:{port}", dial_addr=(host, port)
            )
            if not registered:
                # Accepted TCP but failed the handshake (wrong chain,
                # version skew, peer full, ourselves): demote/forget, or
                # the next tick redials the same dead end forever and
                # starves every other candidate in the book.  (A self-
                # connect already erased the address inside the session —
                # demote leaves absent entries absent.)
                self._demote_addr((host, port))
        finally:
            self._dialing.discard((host, port))

    async def _discovery_loop(self) -> None:
        """Dial learned addresses until ``target_peers`` connections hold
        (SURVEY §1 L5 gossip network, the discovery half: one seed peer
        bootstraps the rest)."""
        last_readdr = 0.0
        while self._running:
            await asyncio.sleep(DISCOVERY_INTERVAL_S)
            # Count node peers only: a long-lived wallet/monitoring client
            # (no advertised address) must not satisfy the target and
            # suppress dialing the real network.
            node_peers = [
                p for p in self._peers.values() if p.addr is not None
            ]
            deficit = self.config.target_peers - len(node_peers)
            if deficit <= 0:
                continue
            # "Connected" covers both spellings of a live peer (its
            # advertised addr AND whatever alias we dialed), and the
            # configured peers are excluded outright — their _dial_loop
            # owns them (including mid-handshake windows where no peer is
            # registered yet).
            connected = {p.addr for p in node_peers}
            connected |= {
                p.dial_addr
                for p in self._peers.values()
                if p.dial_addr is not None
            }
            connected |= set(self.config.peer_addrs())
            started = 0
            # Handshake-verified addresses first: an attacker who filled
            # the gossip book cannot redirect the next dials away from
            # nodes we have actually spoken to.
            for addr in [*self._tried_addrs, *self._known_addrs]:
                if deficit <= started:
                    break
                if addr in connected or addr in self._dialing:
                    continue
                if self._is_banned(addr[0]):
                    continue  # don't court a host we're refusing
                self._dialing.add(addr)
                task = asyncio.create_task(self._dial_once(*addr))
                self._sessions[task] = None
                task.add_done_callback(self._untrack_session)
                started += 1
            now = self.clock.monotonic()
            if (
                started == 0
                and self._peers
                and now - last_readdr >= READDR_INTERVAL_S
            ):
                # Under target with nothing new to dial: periodically ask
                # the peers we DO have for more addresses (new nodes may
                # have joined since the handshake-time GETADDR).  Rate-
                # limited — a node whose target exceeds the network size
                # would otherwise chatter GETADDR every tick forever.
                last_readdr = now
                # Re-ask outbound peers only, crediting each reply —
                # same reasoning as the handshake-time GETADDR: inbound
                # connections must never be able to induce a grant.
                outbound = [
                    p
                    for p in self._peers.values()
                    if p.dial_addr is not None
                ]
                for p in outbound:
                    if p.host:
                        self._addr_budget(p.host, grant=True)
                if outbound:
                    payload = protocol.encode_getaddr()
                    await asyncio.gather(
                        *(self._send_guarded(p, payload) for p in outbound)
                    )

    async def _housekeeping_loop(self) -> None:
        """Periodic pool hygiene: expire transactions that have sat
        unmineable past the configured TTL (mempool.expire)."""
        ttl = self.config.mempool_ttl_s
        interval = max(1.0, min(30.0, ttl / 4)) if ttl > 0 else 30.0
        while self._running:
            await asyncio.sleep(interval)
            if ttl > 0:
                dropped = self.mempool.expire(ttl)
                if dropped:
                    self.log.info(
                        "expired %d stale mempool transactions", dropped
                    )
            # Periodic checkpoint so a crash (not just a clean stop)
            # loses at most one interval's worth of admissions.
            await self._checkpoint_mempool()

    # -- request supervision (sync-stall failover) -----------------------

    async def _request_blocks(self, peer: _Peer) -> None:
        """Issue a supervised locator sync request to ``peer``: the
        progress deadline (re)arms and the supervisor records who to
        blame if nothing lands.  Every GETBLOCKS the node sends to a
        single chosen peer goes through here — the quiesce-time
        ``request_sync`` broadcast is the one exception (it asks
        everyone at once, so there is no staller to supervise)."""
        if self._store_degraded:
            return  # serve-only: don't solicit blocks we would refuse
        if self._snap_fetch is not None:
            # A snapshot download is in flight: replaying history in
            # parallel would just race the download to the tip and waste
            # both (the failure path re-solicits blocks explicitly).
            return
        self._sync.begin(peer)
        await self._send_guarded(
            peer, protocol.encode_getblocks(self.chain.locator())
        )

    async def _request_mempool(
        self, peer: _Peer, cursor: tuple[int, bytes] | None = None
    ) -> None:
        """Issue a supervised mempool (page) request to ``peer``."""
        peer.mempool_requested = True
        peer.mempool_inflight_since = self.clock.monotonic()
        if (
            cursor is None
            and self._recon_enabled()
            and self._recon_peer_active(peer, self.clock.monotonic())
        ):
            # Initial pool sync rides the reconciliation plane when the
            # link supports it: the next tick runs a FULL-pool round
            # (both sides sketch everything they have), so two mostly-
            # overlapping pools cost one sketch exchange instead of
            # re-shipping the whole pool page by page.  The in-flight
            # stamp above keeps ``_check_mempool_sync`` as the safety
            # net either way, and a failed round falls back to classic
            # cursor paging (never a whole-pool flood).
            peer.recon_full_pending = True
            return
        await self._send_guarded(peer, protocol.encode_getmempool(cursor))

    def _pick_sync_peer(self, exclude: _Peer | None = None) -> _Peer | None:
        """The best peer to re-ask: node peers only (a tooling client
        ignores GETBLOCKS), fewest demerits first, taller advertised
        tips breaking ties.  Falls back to the excluded staller itself
        when it is the only peer left — with jittered backoff and a
        bounded attempt budget, retrying the sole source beats giving
        up."""
        candidates = [
            p
            for p in self._peers.values()
            if p.is_node and p is not exclude
        ]
        if not candidates:
            if exclude is not None and exclude.writer in self._peers:
                return exclude
            return None
        return min(
            candidates, key=lambda p: (p.sync_demerits, -p.hello_height)
        )

    async def _supervision_loop(self) -> None:
        """Progress deadlines for every supervised fetch (supervision.py).
        One poll loop rather than a timer per request: all request state
        lives on the event loop anyway, and a tick at a quarter of the
        stall deadline bounds detection latency at ~1.25x the deadline
        without growing a task per in-flight fetch."""
        interval = max(0.05, self.config.sync_stall_timeout_s / 4)
        while self._running:
            await asyncio.sleep(interval)
            now = self.clock.monotonic()
            try:
                await self._check_block_sync()
                await self._check_pending_cblocks(now)
                await self._check_mempool_sync(now)
                await self._check_snapshot_fetch(now)
                await self._check_bg_sync()
            except Exception:
                # The supervisor must never die of one bad tick — it is
                # the layer that un-wedges everything else.
                self.log.exception("request supervision tick failed")

    async def _check_block_sync(self) -> None:
        """The tentpole deadline: an in-flight locator sync that has
        advanced the chain by nothing within ``sync_stall_timeout_s``
        (or whose serving peer disconnected outright) is re-issued to a
        different eligible peer; the staller is demoted, never banned."""
        sup = self._sync
        if not sup.active:
            return
        staller = sup.target
        gone = staller.writer not in self._peers
        if not (gone or sup.stalled()):
            return
        self.metrics.sync_stalls += 1
        if not gone:
            staller.sync_demerits += 1
            self.metrics.sync_demotions += 1
            self.log.warning(
                "sync stall: %s advanced nothing in %.1fs — demoting "
                "and failing over",
                staller.label,
                sup.stall_timeout_s,
            )
        if sup.exhausted():
            # Budget spent on consecutive no-progress failovers: stop
            # chasing until something new triggers a sync (fresh HELLO,
            # orphan, compact push) — which also starts a fresh budget.
            self.metrics.sync_exhausted += 1
            sup.attempts = 0
            sup.idle()
            self.log.warning(
                "sync failover budget exhausted (%d attempts); waiting "
                "for a fresh trigger",
                sup.attempts_max,
            )
            return
        delay = sup.record_stall()
        # Supervision timing: the jittered backoff each stall armed —
        # with the stall deadline itself, the latency a starved sync
        # episode pays before its failover lands.
        self.telemetry.observe("sync.backoff_s", delay)
        task = asyncio.create_task(self._failover_blocks(staller, delay))
        self._sessions[task] = None
        task.add_done_callback(self._untrack_session)

    async def _failover_blocks(self, staller: _Peer, delay: float) -> None:
        """After the jittered backoff, re-issue the locator to the best
        non-stalling peer (selection deferred to AFTER the sleep — the
        peer set may have changed meanwhile)."""
        await asyncio.sleep(delay)
        if not self._running:
            return
        candidate = self._pick_sync_peer(exclude=staller)
        if candidate is None:
            # Nobody connected to ask: the dial/discovery loops own
            # reconnection, and a fresh handshake restarts the sync.
            return
        self.metrics.sync_failovers += 1
        self.log.info(
            "sync failover: re-issuing locator to %s", candidate.label
        )
        await self._request_blocks(candidate)

    async def _check_pending_cblocks(self, now: float) -> None:
        """A GETBLOCKTXN round trip that outlives the stall deadline is
        abandoned: the reconstruction is dropped, the silent peer
        demoted, and the block recovered through ordinary supervised
        locator sync — a compact push must never be the only way a
        block can arrive (the FIFO cap alone left stranded entries
        squatting until MAX_PENDING_CBLOCKS newer pushes evicted
        them)."""
        deadline = self.config.sync_stall_timeout_s
        stale = [
            key
            for key, pending in self._pending_cblocks.items()
            if now - pending.asked_at > deadline
        ]
        if not stale:
            return
        last_staller = None
        for key in stale:
            del self._pending_cblocks[key]
            bhash, peer = key
            self.metrics.cblock_fetch_stalls += 1
            if peer.writer in self._peers:
                peer.sync_demerits += 1
                self.metrics.sync_demotions += 1
            last_staller = peer
            self.log.warning(
                "GETBLOCKTXN to %s stalled %.1fs — dropping "
                "reconstruction of %s, recovering via locator sync",
                peer.label,
                deadline,
                bhash.hex()[:16],
            )
        candidate = self._pick_sync_peer(exclude=last_staller)
        if candidate is not None:
            self.metrics.sync_failovers += 1
            await self._request_blocks(candidate)

    async def _check_mempool_sync(self, now: float) -> None:
        """A mempool page request with no MEMPOOL reply inside the
        deadline: stop waiting on that peer (demote) and solicit the
        pool from one other idle peer — pools overlap heavily, so any
        honest peer recovers most of what the staller withheld."""
        deadline = self.config.sync_stall_timeout_s
        for peer in list(self._peers.values()):
            since = peer.mempool_inflight_since
            if since is None or now - since <= deadline:
                continue
            peer.mempool_inflight_since = None
            self.metrics.mempool_sync_stalls += 1
            peer.sync_demerits += 1
            self.metrics.sync_demotions += 1
            self.log.warning(
                "mempool sync with %s stalled %.1fs — asking another "
                "peer",
                peer.label,
                deadline,
            )
            other = self._pick_sync_peer(exclude=peer)
            if (
                other is not None
                and other is not peer
                and other.mempool_inflight_since is None
            ):
                await self._request_mempool(other)

    def _learn_addr(self, addr: tuple[str, int], tried: bool = False) -> None:
        """Merge one address into the bounded book (refreshes recency).
        ``tried`` promotes it to the handshake-verified bucket, where
        gossip-driven churn can never reach it."""
        if tried:
            self._known_addrs.pop(addr, None)
            self._tried_addrs.pop(addr, None)
            self._tried_addrs[addr] = self.clock.monotonic()
            while len(self._tried_addrs) > MAX_TRIED_ADDRS:
                self._tried_addrs.popitem(last=False)
            return
        if addr in self._tried_addrs:
            return  # already known-good; gossip cannot demote it
        self._known_addrs.pop(addr, None)
        self._known_addrs[addr] = self.clock.monotonic()
        while len(self._known_addrs) > MAX_KNOWN_ADDRS:
            self._known_addrs.popitem(last=False)

    def _forget_addr(self, addr: tuple[str, int]) -> None:
        """Drop an address from both buckets (dead, or ourselves)."""
        self._known_addrs.pop(addr, None)
        self._tried_addrs.pop(addr, None)

    def _demote_addr(self, addr: tuple[str, int]) -> None:
        """One failed dial: a tried address loses its protected status
        but stays as a rumor (a real node may be mid-restart — exactly
        when an eclipse attacker wants it erased for good); an unproven
        one is forgotten outright.  An address absent from both buckets
        (e.g. already dropped as a self-connect) stays absent."""
        if self._tried_addrs.pop(addr, None) is not None:
            self._learn_addr(addr)  # back to the gossip book
        else:
            self._known_addrs.pop(addr, None)

    def _addr_budget(self, host: str, grant: bool = False) -> list[float]:
        """The host's refilled ADDR token bucket ([tokens, last_refill]).
        ``grant`` ADDS one reply's worth of credit (bounded) — used when
        WE solicit with a GETADDR, so each reply we ask for fits the
        budget even when several outbound peers share one host (the
        localhost mesh).  Grants are additive rather than set-to-max
        because two same-host solicited replies would otherwise race for
        a single refill; safe because only our own outbound dials can
        trigger a grant, never an inbound peer."""
        now = self.clock.monotonic()
        bucket = self._addr_budgets.get(host)
        if bucket is None:
            bucket = self._addr_budgets[host] = [ADDR_TOKENS_MAX, now]
            if len(self._addr_budgets) > MAX_TRACKED_HOSTS:
                # Drop only buckets that are BOTH stale and sitting at
                # exactly the base refill — those provably carry no
                # state (they equal what a fresh create would mint).
                # Everything else is information: recent activity, spent
                # budget mid-window, and above all tokens ABOVE the cap,
                # which are solicited-reply credit granted to an
                # outbound peer — clawing that back mid-reply would
                # silently ignore part of an ADDR answer we asked for
                # (ADVICE r5: the old `< ADDR_TOKENS_MAX` filter did
                # exactly that).
                refill_s = ADDR_TOKENS_MAX / ADDR_TOKENS_RATE
                cutoff = now - refill_s
                self._addr_budgets = {
                    h: b
                    for h, b in self._addr_budgets.items()
                    if b[1] >= cutoff or b[0] != ADDR_TOKENS_MAX
                }
                self._addr_budgets.setdefault(host, bucket)
                while len(self._addr_budgets) > MAX_TRACKED_HOSTS:
                    del self._addr_budgets[next(iter(self._addr_budgets))]
        elif grant:
            bucket[0] = min(4 * ADDR_TOKENS_MAX, bucket[0] + ADDR_TOKENS_MAX)
            bucket[1] = now
        else:
            if bucket[0] < ADDR_TOKENS_MAX:
                # Trickle refill toward the base cap; never claw back
                # grant credit sitting above it.
                bucket[0] = min(
                    ADDR_TOKENS_MAX,
                    bucket[0] + (now - bucket[1]) * ADDR_TOKENS_RATE,
                )
            bucket[1] = now
        return bucket

    async def _peer_session(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        label: str,
        dial_addr: tuple[str, int] | None = None,
        inbound: bool = False,
    ) -> bool:
        """Run one peer session to completion.  Returns whether the peer
        ever completed the handshake and registered — False means the
        address is not worth redialing (discovery forgets it).

        Liveness contract (the layer every Bitcoin-family node carries):
        the HELLO must arrive within ``handshake_timeout_s``; after that a
        peer silent for ``ping_interval_s`` is probed with a PING and gets
        ``pong_timeout_s`` more to show ANY frame before eviction.  So a
        socket can hold one of the MAX_PEERS slots only while provably
        alive, and a pre-handshake socket (counted in ``_handshaking``)
        for at most the handshake window."""
        peer = _Peer(writer, label, self.metrics)
        peer.dial_addr = dial_addr
        peer.budget = self.governor.budget()
        registered = False
        # All session reads go through one FrameReader: timeouts cancel
        # reads at arbitrary awaits, and only a reader that keeps partial
        # progress itself can resume at the same stream position (a plain
        # read_frame cancelled between length prefix and body would desync
        # the stream and mis-score the peer).
        frames = protocol.FrameReader(reader, clock=self.clock.monotonic)
        if inbound:
            self._handshaking += 1
        try:
            if len(self._peers) >= MAX_PEERS:
                raise _Refused(f"peer limit {MAX_PEERS} reached")
            # Height at the moment our HELLO leaves: if the chain moves
            # during the handshake round trip, the advertisement below
            # is stale and must be corrected (see the tip push after
            # registration).
            hello_sent_height = self.chain.height
            await peer.send(self._hello())
            # Deadline on the whole HELLO read: a socket that connects and
            # goes quiet must not hold resources past this window.  A
            # TimeoutError lands in TimeoutError ⊂ OSError below — reaped,
            # not scored (slowness is not a protocol violation).
            payload = await asyncio.wait_for(
                frames.read(), timeout=self.config.handshake_timeout_s
            )
            self.metrics.bytes_received += len(payload) + 4
            mtype, hello = protocol.decode(payload)
            if mtype is not MsgType.HELLO:
                raise protocol.ProtocolError("expected HELLO")
            if hello.genesis_hash != self.chain.genesis.block_hash():
                raise protocol.ChainMismatch("genesis mismatch")
            if hello.nonce and hello.nonce == self.instance_nonce:
                # We dialed our own listening address (the book can learn
                # it from peers' ADDR gossip) — drop it for good.
                if dial_addr is not None:
                    self._forget_addr(dial_addr)
                raise _Refused("connected to self")
            if len(self._peers) >= MAX_PEERS:
                # Re-check at registration: the pre-handshake check above
                # races across the two awaits (a flood of simultaneous
                # dials all pass it while _peers is still small).
                raise _Refused(f"peer limit {MAX_PEERS} reached")
            self._peers[writer] = peer
            registered = True
            if inbound:
                self._handshaking -= 1
                inbound = False  # the finally below must not double-count
            self.log.info("peer %s connected (their height %d)", label, hello.tip_height)
            peer.hello_height = hello.tip_height
            peer.is_node = bool(hello.nonce)  # 0 = one-shot tooling client
            if hello.nonce:
                # Pairwise short-id salt for set reconciliation: both
                # ends derive the identical value from the sorted nonce
                # pair, so sketches agree without any extra negotiation.
                # Derived unconditionally (even with recon disabled): a
                # recon-off node still ANSWERS REQRECON with a sketch of
                # what it has, keeping straggler meshes correct.
                peer.recon_salt = reconcile.pair_salt(
                    self.instance_nonce, hello.nonce
                )
            if hello.listen_port:
                # The peer's claimed reachable address: its socket host +
                # the listen port it advertised.  NOT promoted to tried —
                # the port is self-claimed and unverified, and an inbound
                # attacker completing 256 cheap HELLOs with rotating port
                # claims would otherwise flush the whole tried bucket.
                # Charged against the same per-host ADDR budget as gossip:
                # a reconnect loop claiming a new port each time is just
                # an ADDR flood spelled differently.
                peername = writer.get_extra_info("peername")
                if peername:
                    peer.addr = (peername[0], hello.listen_port)
                    bucket = self._addr_budget(peername[0])
                    if bucket[0] >= 1.0:
                        bucket[0] -= 1.0
                        self._learn_addr(peer.addr)
            if dial_addr is not None:
                # Tried promotion is outbound-only (Bitcoin's rule, for
                # Bitcoin's reason): WE dialed this exact address and a
                # real node answered — that is verified reachability,
                # which no inbound claim can counterfeit.
                self._learn_addr(dial_addr, tried=True)
            if hello.nonce and dial_addr is not None:
                # Solicit addresses on OUTBOUND connections only
                # (Bitcoin's rule): an inbound attacker could otherwise
                # induce the ask and ride the solicited budget grant to
                # flush the gossip book by reconnecting.  We control the
                # dial rate, so the grant is attacker-independent.
                if peer.host:
                    self._addr_budget(peer.host, grant=True)
                await peer.send(protocol.encode_getaddr())
            if peer.is_node and self.chain.height > hello_sent_height:
                # The chain moved while the handshake was in flight, so
                # the height we advertised is stale and the peer may
                # have (correctly, on its information) decided not to
                # sync from us.  Push the current tip once: the peer
                # connects it or orphan-backfills through ordinary
                # locator sync.  Without this, a block that lands
                # during the handshake RTT is never advertised on the
                # new link at all — on a WAN-latency simulated mesh,
                # every cross-region link formed during one block's
                # propagation window went dark this way and a region
                # mined a competing fork (node/netsim.py found it; a
                # ~100 ms race real-socket tests never hit).
                payload, _saved = self._block_gossip_payload(self.chain.tip)
                await self._send_guarded(peer, payload)
            if self._snapshot_worthwhile(peer):
                # Fresh node, far-ahead peer, snapshot sync enabled:
                # fetch a state snapshot instead of replaying history —
                # boot-from-snapshot in seconds, with the robustness
                # contract (verify, ASSUME, revalidate, flip-or-
                # quarantine) carried by the snapshot plane above.
                await self._request_snapshot(peer)
            elif hello.tip_height > self.chain.height:
                # Blocks first, mempool after: the BLOCKS handler requests
                # the pool once our chain reaches the advertised height,
                # so admission's affordability check runs against a
                # caught-up ledger.  Supervised: a peer that advertises a
                # taller tip and then starves the sync is failed over
                # within the progress deadline (_check_block_sync).
                await self._request_blocks(peer)
            else:
                # Learn the peer's pending transactions too: block sync
                # alone would leave a late joiner's pool empty until fresh
                # gossip.
                await self._request_mempool(peer)
            ping_pending = False
            while self._running:
                # Idle probing: wait ping_interval_s for traffic; on
                # silence send one PING and allow pong_timeout_s more.
                # ANY frame proves liveness (resets the probe) — the PONG
                # itself is never specifically required, so a peer busy
                # streaming sync batches is never penalized for not
                # answering promptly.  Byte-level progress counts too: a
                # peer trickling one large frame over a slow link shows
                # ``frames.progressed()`` at each timeout and is left
                # alone — only true silence is probed and evicted.
                timeout = (
                    self.config.pong_timeout_s
                    if ping_pending
                    else self.config.ping_interval_s
                )
                try:
                    payload = await asyncio.wait_for(
                        frames.read(), timeout=timeout
                    )
                except (TimeoutError, asyncio.TimeoutError):
                    # Both spellings: asyncio.TimeoutError only became the
                    # builtin in Python 3.11; on 3.10 a bare TimeoutError
                    # would miss it and the probe path would never run.
                    grace = (
                        self.config.ping_interval_s
                        + self.config.pong_timeout_s
                    )
                    if frames.progressed() and not frames.overdue(grace):
                        ping_pending = False  # flowing, just slowly
                        continue
                    # Overdue trickle falls through to the probe path: one
                    # more PING + pong_timeout, then eviction — same reap,
                    # no misbehavior score (slowness is not a violation).
                    if ping_pending:
                        self.metrics.peers_evicted_idle += 1
                        raise _Refused(
                            f"peer idle past keepalive deadline "
                            f"({self.config.ping_interval_s:.0f}s + "
                            f"{self.config.pong_timeout_s:.0f}s probe)"
                        ) from None
                    ping_pending = True
                    self.metrics.pings_sent += 1
                    await self._send_guarded(
                        peer, protocol.encode_ping(self.instance_nonce)
                    )
                    continue
                ping_pending = False
                self.metrics.bytes_received += len(payload) + 4
                await self._dispatch(peer, payload)
        except (
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,  # pre-3.11: not an OSError subclass
            ConnectionError,
            ValueError,
            OSError,
            _Refused,
        ) as e:
            self.log.info("peer %s closed: %s", label, e)
            if isinstance(e, protocol.ProtocolError) and not isinstance(
                e, protocol.ChainMismatch
            ):
                # Peer-side protocol violation (malformed frame, bad
                # handshake bytes) — score it; repeat offenders get
                # refused at accept time for a cooldown.  Plain
                # ValueErrors stay unscored (they can originate in OUR
                # encode paths while answering an innocent peer), and so
                # do well-formed HELLOs for the wrong chain or version:
                # that is misconfiguration — e.g. a wallet run with the
                # wrong --difficulty — not hostility, and scoring it
                # would let three such invocations ban loopback.
                peername = writer.get_extra_info("peername")
                if peername:
                    self._record_violation(peername[0])
        finally:
            if inbound:  # still mid-handshake: release the slot
                self._handshaking -= 1
            self.subscriptions.drop(writer)
            self._peers.pop(writer, None)
            writer.close()
        return registered

    async def _dispatch(self, peer: _Peer, payload: bytes) -> None:
        # Wire-frame stage span: the decode cost a frame pays before any
        # admission or state work (the block pipeline's first leg).
        # ``clk`` is None iff telemetry is disabled; ``sclk`` is
        # additionally None on the 7-of-8 frames the micro-stage
        # sampler skips (see _tel_tick) — frame/admission ride sclk,
        # query latency below rides clk and records every event.
        clk = self._tel_clock
        sclk = None
        if clk is not None:
            self._tel_tick += 1
            if not (self._tel_tick & 7):
                sclk = clk
        if sclk is not None:
            t0 = sclk()
            mtype, body = protocol.decode(payload)
            self._h_frame.observe(sclk() - t0)
        else:
            mtype, body = protocol.decode(payload)
        # Overload front door (node/governor.py), BEFORE any state or
        # compute is spent on the frame.  SHED drops low-priority
        # traffic wholesale; admission charges the peer's class budget
        # for everything unsolicited and drops the excess — sustained
        # flooding escalates to the ordinary misbehavior score (and so,
        # eventually, to the accept-time ban).
        if self.governor.shedding and mtype in _SHED_DROPS:
            if mtype is MsgType.MEMPOOL:
                # Not the peer's fault we refused its page: don't let the
                # in-flight marker age into a stall demerit.
                peer.mempool_inflight_since = None
            elif mtype is MsgType.SKETCH:
                # Same courtesy for a shed sketch reply: close the round
                # without a demerit, re-queueing what it carried so the
                # txs retry once the pressure clears (no fallback flood
                # — under SHED the tx plane is being shed wholesale).
                peer.recon_round.update(peer.recon_pending)
                peer.recon_pending = peer.recon_round
                peer.recon_round = {}
                peer.recon_round_full = False
                peer.recon_inflight_since = None
            self.governor.shed_drop()
            return
        cls = _MSG_CLASS.get(mtype)
        if cls is not None:
            if sclk is not None:
                t0 = sclk()
                admitted = self.governor.admit(peer.budget, cls)
                self._h_admission.observe(sclk() - t0)
            else:
                admitted = self.governor.admit(peer.budget, cls)
            if not admitted:
                if peer.budget.owes_violation(cls) and peer.host:
                    self.log.warning(
                        "admission budget exceeded: dropping %s flood from %s",
                        cls,
                        peer.label,
                    )
                    self._record_violation(peer.host)
                    if self._is_banned(peer.host):
                        # The score just crossed the ban threshold: sever
                        # the live session too — the accept-time refusal
                        # alone would let the flooder keep this socket for
                        # the whole ban and never feel it.
                        raise _Refused(
                            f"{cls} flood from {peer.label}: banned"
                        )
                return
        # Query-plane request latency: one admitted GET* frame from
        # decode-done to reply-sent (every branch below falls through to
        # the common exit, so one stamp pair covers them all).
        query_t0 = (
            clk() if clk is not None and cls == CLASS_QUERIES else None
        )
        if mtype is MsgType.BLOCK:
            sent_ts, block = body
            await self._handle_block(block, origin=peer, sent_ts=sent_ts)
        elif mtype is MsgType.TX:
            await self._handle_tx(body, origin=peer)
        elif mtype is MsgType.GETBLOCKS:
            if (
                self.chain.prune_floor
                and self.chain.sync_start_height(body) < self.chain.prune_floor
            ):
                # Pruned-range refusal (round 18): the bodies below the
                # prune floor were discarded by policy.  Answer with an
                # EMPTY batch instead of disconnecting — an honest
                # syncing peer reads it as a stall and fails over to an
                # archive peer (node/supervision.py); our ``pruned``
                # status field lets it avoid us up front.
                self.metrics.pruned_refusals += 1
                await self._send_guarded(peer, protocol.encode_blocks([]))
            else:
                try:
                    blocks = self.chain.blocks_after(body, limit=SYNC_BATCH)
                except OSError as e:
                    # A segment went EIO under a body refetch: degrade
                    # to serve-only (the PR 3 recovery loop re-probes
                    # the disk) but keep THIS session — the fault is
                    # the disk's, not the peer's.
                    self._store_fail(e)
                    blocks = []
                # Cap the reply by encoded bytes too: with ~half-KB txs
                # a 500-block batch can exceed the receiver's frame
                # cap, which would wedge sync in a reconnect loop.
                capped, total = [], 0
                for blk in blocks:
                    total += len(blk.serialize()) + 4
                    if capped and total > SYNC_BYTES:
                        break
                    capped.append(blk)
                await self._send_guarded(
                    peer, protocol.encode_blocks(capped)
                )
        elif mtype is MsgType.BLOCKS:
            # Batch the store's durability: per-append fsync (~2 ms) is
            # right for the one-block gossip cadence but would stall this
            # event loop for seconds across a deep resync batch — and a
            # crash mid-batch only loses blocks the peer will re-serve.
            batch_fsync = self.store is not None and self.store.fsync
            if batch_fsync:
                self.store.fsync = False
            # VALIDATE stage: prove the whole batch's transfer
            # signatures into the verify-once cache with one batched
            # call before the per-block connect loop — a deep-sync reply
            # of 500 tx-bearing blocks pays the Ed25519 backend once,
            # not per transfer, and on the pipeline's validate lane the
            # ctypes engine (which releases the GIL) runs off-loop.
            # Purely a cache-warmer: per-block check_block still
            # decides, with identical outcomes
            # (chain/validate.py preverify_signatures).  The generator
            # hands the lane the same tx objects the frame decoded —
            # zero-copy, no re-encode.
            await self.pipeline.run_validate(
                preverify_signatures,
                (tx for block in body for tx in block.txs),
                self.chain.genesis.block_hash(),
                self.sig_cache,
                nbytes=sum(len(block.serialize()) for block in body),
            )
            accepted_any = False
            bg_accepted = 0
            try:
                for block in body:
                    # Content routing while a background revalidation is
                    # running (ASSUMED state): historical blocks — the
                    # ones only the genesis-anchored background chain
                    # can connect — feed IT; blocks the serving chain
                    # knows how to place take the normal path (both, for
                    # the overlap around the snapshot anchor).  Without
                    # the split, history would park as orphans in the
                    # assumed chain and never validate anything.
                    bg = self._bg_chain
                    handled = False
                    if bg is not None and (
                        block.block_hash() in bg
                        or block.header.prev_hash in bg
                    ):
                        st = bg.add_block(block)
                        if st.status is AddStatus.ACCEPTED and st.connected:
                            bg_accepted += len(st.connected)
                            self.metrics.revalidated_blocks += len(
                                st.connected
                            )
                            self._bg_sup.progress()
                        handled = True
                    if bg is None or (
                        block.block_hash() in self.chain
                        or block.header.prev_hash in self.chain
                    ):
                        res = await self._handle_block(
                            block, origin=peer, gossip=False
                        )
                        accepted_any |= res.status is AddStatus.ACCEPTED
                        handled = True
                    if not handled:
                        # Neither chain knows the parent: a gap in the
                        # history fetch — park in the background chain's
                        # bounded orphan pool, never the serving one's.
                        bg.add_block(block)
            finally:
                if batch_fsync:
                    self.store.fsync = True
                    await self._store_sync_staged()
            if bg_accepted:
                # The replay advanced: verdict check (flip/diverge), and
                # if still running, keep pulling history from this peer.
                await self._check_bg_done()
                if self._bg_chain is not None and body:
                    await self._bg_request(peer)
            # Progress was made and the batch was non-empty: there may be
            # more behind it (an empty/duplicate reply ends the loop).
            if accepted_any and body:
                await self._request_blocks(peer)
            else:
                if self._announce_tip:
                    # Catch-up quiesced on a new tip: announce it once
                    # (see _announce_tip).  Receivers that followed the
                    # same sync dedup it for the cost of one frame;
                    # receivers beyond a healed cut learn the chain
                    # exists and pull the rest via orphan backfill.
                    # The announce must NOT skip the quiescing peer:
                    # with interleaved catch-up episodes the tip can
                    # come from a different peer entirely, and the one
                    # whose empty reply quiesced us may be BEHIND it —
                    # a crash-recovered node that synced 2->4 from a
                    # stale peer and 4->7 from a fresh one consumed the
                    # one-shot flag on the stale peer's quiesce and
                    # skipped exactly the node that needed the push,
                    # leaving it forked forever (found by the chaos
                    # sweep, node/chaos.py seed 30; the redundant frame
                    # to an already-caught-up server is one dedup).
                    self._announce_tip = False
                    payload, saved = self._block_gossip_payload(
                        self.chain.tip
                    )
                    n = await self._gossip(payload)
                    if saved and n:
                        self.metrics.cblocks_sent += n
                        self.metrics.cblock_bytes_saved += saved * n
                if (
                    self._sync.target is peer
                    and self.chain.height >= peer.hello_height
                ):
                    # The supervised sync quiesced AND delivered what the
                    # peer advertised: a completed episode, not a stall.
                    # A non-advancing reply BELOW the advertised height
                    # (empty frames, re-served stale batches) leaves the
                    # deadline armed instead — chatty uselessness must
                    # read as a stall, or it would be the cheapest way
                    # to defeat the failover.  (A different peer's sync
                    # stays armed either way.)
                    self._sync.idle()
                if (
                    not peer.mempool_requested
                    and self.chain.height >= peer.hello_height
                ):
                    # Block sync with this peer quiesced AND our chain
                    # reached what it advertised: NOW ask for its pool,
                    # with our ledger caught up (one-shot per peer).  If
                    # another peer's sync is still filling the gap, the
                    # next quiesced batch re-checks.
                    await self._request_mempool(peer)
        elif mtype is MsgType.GETMEMPOOL:
            page, more = self.mempool.sync_page(body, MEMPOOL_SYNC_TXS)
            raws, total = [], 0
            for tx in page:
                raw = tx.serialize()
                total += len(raw) + 2
                if raws and total > MEMPOOL_SYNC_BYTES:
                    more = True  # byte-trimmed: the rest is still out there
                    break
                raws.append(raw)
            await self._send_guarded(peer, protocol.encode_mempool(raws, more))
        elif mtype is MsgType.MEMPOOL:
            more, txs = body
            peer.mempool_inflight_since = None  # page landed: not stalled
            # VALIDATE stage: batch the page's signatures into the
            # verify-once cache before per-tx admission (same fast lane
            # as deep-sync block batches; outcomes unchanged), off-loop
            # on the pipeline's validate lane.
            await self.pipeline.run_validate(
                preverify_signatures,
                txs,
                self.chain.genesis.block_hash(),
                self.sig_cache,
                nbytes=sum(len(tx.serialize()) for tx in txs),
            )
            for tx in txs:
                await self._handle_tx(tx, origin=peer)
            if more:
                # Continue from the largest key received, and only if it
                # strictly advances — key-ordering is (-fee, txid), so a
                # responder replaying old keys can't spin the sync.
                from p1_tpu.mempool import sync_key

                cursor = None
                if txs:
                    last = max(txs, key=lambda t: sync_key(t.fee, t.txid()))
                    cursor = (last.fee, last.txid())
                prev = peer.mempool_cursor
                if cursor is not None and (
                    prev is None or sync_key(*cursor) > sync_key(*prev)
                ):
                    peer.mempool_cursor = cursor
                    await self._request_mempool(peer, cursor)
                else:
                    # "More coming" with an empty or non-advancing tail:
                    # chatty uselessness, and before round 23 it simply
                    # ENDED the sync silently — a hostile responder
                    # could park a node's pool sync forever at zero
                    # cost.  It now reads as the stall it is: demote and
                    # re-solicit from one other idle peer, same recovery
                    # as the in-flight deadline path.
                    self.metrics.mempool_sync_stalls += 1
                    peer.sync_demerits += 1
                    self.metrics.sync_demotions += 1
                    self.log.warning(
                        "mempool sync with %s stopped advancing — asking "
                        "another peer",
                        peer.label,
                    )
                    other = self._pick_sync_peer(exclude=peer)
                    if (
                        other is not None
                        and other is not peer
                        and other.mempool_inflight_since is None
                    ):
                        await self._request_mempool(other)
        elif mtype is MsgType.GETACCOUNT:
            # Wallet/CLI query: consensus state at OUR tip plus the next
            # usable seq net of our pending pool (p1 tx auto-seq).
            nonce = self.chain.nonce(body)
            await self._send_guarded(
                peer,
                protocol.encode_account(
                    protocol.AccountState(
                        body,
                        self.chain.balance(body),
                        nonce,
                        self.mempool.pending_next_seq(body, nonce),
                        self.chain.height,
                    )
                ),
            )
        elif mtype is MsgType.CBLOCK:
            await self._handle_cblock(body, peer)
        elif mtype is MsgType.GETBLOCKTXN:
            bhash, indices = body
            block = self.chain.get(bhash)
            if block is not None and indices[-1] < len(block.txs):
                await self._send_guarded(
                    peer,
                    protocol.encode_blocktxn(
                        bhash, [block.txs[i].serialize() for i in indices]
                    ),
                )
            # Unknown block / out-of-range indices: ignore — the requester
            # falls back to locator sync, and answering garbage helps no one.
        elif mtype is MsgType.BLOCKTXN:
            await self._handle_blocktxn(body, peer)
        elif mtype is MsgType.GETFEES:
            # Wallet fee query: confirmed-fee percentiles at our tip.
            stats = self.chain.fee_stats(min(body or 32, FEE_WINDOW_MAX))
            await self._send_guarded(
                peer,
                protocol.encode_fees(
                    protocol.FeeStats(
                        stats["window_blocks"],
                        stats["samples"],
                        stats["p25"],
                        stats["p50"],
                        stats["p75"],
                        self.chain.height,
                    )
                ),
            )
        elif mtype is MsgType.FEES:
            pass  # reply frame: meaningful to querying clients only
        elif mtype is MsgType.GETADDR:
            # Share listening addresses we know, minus the asker's own
            # (it does not need to learn itself): every tried address
            # first (handshake-verified beats rumor), newest gossip after.
            tried = [a for a in self._tried_addrs if a != peer.addr]
            addrs = tried[-ADDR_REPLY_MAX:]
            room = ADDR_REPLY_MAX - len(addrs)
            if room > 0:
                addrs += [
                    a for a in self._known_addrs if a != peer.addr
                ][-room:]
            await self._send_guarded(peer, protocol.encode_addr(addrs))
        elif mtype is MsgType.ADDR:
            # Per-HOST token bucket: one host must not be able to churn
            # the whole gossip book by streaming ADDR frames — nor by
            # reconnecting for fresh budgets (and tried addresses are out
            # of reach regardless).  Over-budget entries are ignored, not
            # scored — ADDR is advisory.
            bucket = (
                self._addr_budget(peer.host) if peer.host else [0.0, 0.0]
            )
            for addr in body[:ADDR_REPLY_MAX]:  # cap hostile batches
                if bucket[0] < 1.0:
                    break
                bucket[0] -= 1.0
                self._learn_addr(addr)
        elif mtype is MsgType.GETHEADERS:
            # Headers-first sync for light clients: same locator
            # semantics as GETBLOCKS, 80 B/block on the wire.  Served
            # from the always-resident header index (``headers_after``)
            # — never a body refetch, so header sync keeps working over
            # pruned and evicted ranges.
            headers = self.chain.headers_after(body, limit=HEADERS_BATCH)
            await self._send_guarded(
                peer, protocol.encode_headers(headers)
            )
        elif mtype is MsgType.HEADERS:
            pass  # reply frame: meaningful to light clients only
        elif mtype is MsgType.GETPROOF:
            # SPV query: serve the inclusion proof (or not-found) from the
            # chain's txid index; the client verifies it, we just attest
            # our main-chain view.  Served through the proof cache
            # (chain/proof.py): a repeat query is a payload memo hit plus
            # a 4-byte tip-height patch, a cold one fills proof templates
            # for the whole containing block in one merkle pass.
            await self._send_guarded(peer, self._proof_payload(body))
        elif mtype is MsgType.GETFILTERS:
            # Light-client filter sync (chain/filters.py): the compact
            # filters for a main-chain height range, each pinned to its
            # block hash.  Range-capped like GETBLOCKS/GETHEADERS so one
            # query can't drive an O(chain) scan on the event loop.
            start, count = body
            entries = []
            for h in range(start, start + min(count, FILTER_BATCH)):
                bhash = self.chain.main_hash_at(h)
                if bhash is None:
                    break
                fbytes = self.chain.block_filter(bhash)
                entries.append((bhash, fbytes))
            self.metrics.filters_served += len(entries)
            self.metrics.filter_bytes_served += sum(
                len(f) for _, f in entries
            )
            await self._send_guarded(
                peer, protocol.encode_filters(start, entries)
            )
        elif mtype is MsgType.FILTERS:
            pass  # reply frame: meaningful to light clients only
        elif mtype is MsgType.GETFILTERHEADERS:
            # The BIP157-analog commitment chain (chain/filters.py): the
            # proof surface a wallet cross-checks untrusted filter
            # streams against.  ``range`` refuses (empty reply) rather
            # than partially answer a span this chain has not committed
            # — pruned/rebased nodes are honestly short, never wrong.
            start, count = body
            await self._send_guarded(
                peer,
                protocol.encode_filterheaders(
                    start,
                    self.chain.filter_headers.range(
                        start, min(count, FILTER_BATCH)
                    ),
                ),
            )
        elif mtype is MsgType.FILTERHEADERS:
            pass  # reply frame: meaningful to light clients only
        elif mtype is MsgType.SUBSCRIBE:
            # Wallet push plane (node/subscriptions.py): register this
            # session's watch items; an unverifiable resume cursor is
            # refused by disconnect (unscored — a pruned window or a
            # wallet that last spoke to a liar is not hostility), which
            # is the wallet's signal to fail over.
            cursor, items = body
            sub_writer = peer.writer

            async def _sub_push(payload: bytes, w=sub_writer) -> None:
                protocol.write_frame_nowait(w, payload)

            def _sub_buf(w=sub_writer) -> int:
                transport = w.transport
                return (
                    transport.get_write_buffer_size()
                    if transport is not None
                    else 0
                )

            ok = await self.subscriptions.subscribe(
                sub_writer,
                items,
                cursor,
                send=_sub_push,
                buffer_size=_sub_buf,
                close=sub_writer.close,
            )
            if not ok:
                raise _Refused("resume cursor not on the committed chain")
        elif mtype is MsgType.UNSUBSCRIBE:
            self.subscriptions.unsubscribe(peer.writer)
        elif mtype is MsgType.EVENT:
            pass  # push frame: meaningful to subscribed wallets only
        elif mtype is MsgType.GETSNAPSHOT:
            # Snapshot serving (chain/snapshot.py): manifest or a chunk
            # range of the latest checkpoint state.  Range-capped and
            # governor-admitted like every other query; an ASSUMED node
            # (or a chain too short for a checkpoint) answers "none".
            start, count = body
            records = self._snapshot_records()
            if records is None:
                await self._send_guarded(peer, protocol.encode_snapshot_none())
            elif count == 0:
                await self._send_guarded(
                    peer, protocol.encode_snapshot_manifest(records[0])
                )
            else:
                chunks = records[1][start : start + min(count, SNAPSHOT_BATCH)]
                self.metrics.snapshot_chunks_served += len(chunks)
                await self._send_guarded(
                    peer, protocol.encode_snapshot_chunks(start, chunks)
                )
        elif mtype is MsgType.SNAPSHOT:
            await self._handle_snapshot(body, peer)
        elif mtype is MsgType.GETSTATUS:
            # Operator probe (`p1 status`): the same JSON the node logs,
            # served over the wire — deliberately NOT in _SHED_DROPS, so
            # overload stays observable while it is happening.
            await self._send_guarded(
                peer, protocol.encode_status(self.status())
            )
        elif mtype is MsgType.GETMETRICS:
            # Telemetry probe (`p1 metrics`): the registry snapshot —
            # per-stage latency histograms, counters, gauges.  IS shed
            # under overload (unlike GETSTATUS): scrapers retry.
            await self._send_guarded(
                peer, protocol.encode_metrics(self.telemetry_snapshot())
            )
        elif mtype is MsgType.GETMAINTAIN:
            # Maintenance command (`p1 maintain`): live re-basing,
            # online prune/compact, version-bits status — executed
            # inline on the dispatch loop (the ops themselves push
            # their heavy halves onto the store lane), refusals
            # answered as {"ok": false}, never dropped sessions.
            await self._send_guarded(
                peer, protocol.encode_maintain(await self._maintain(body))
            )
        elif mtype in (MsgType.STATUS, MsgType.METRICS, MsgType.MAINTAIN):
            pass  # reply frames: meaningful to querying clients only
        elif mtype is MsgType.PING:
            await self._send_guarded(peer, protocol.encode_pong(body))
        elif mtype is MsgType.PONG:
            pass  # arrival already reset the session's idle probe
        elif mtype in (MsgType.ACCOUNT, MsgType.PROOF):
            pass  # reply frames: meaningful to querying clients only
        elif mtype is MsgType.REQRECON:
            # Responder half of a reconciliation round: freeze our queue
            # for this link (merging any window a vanished initiator
            # left behind) and serve a sketch sized for the estimated
            # difference.  Served even when recon is locally disabled —
            # a sketch of what we have is one small frame and keeps
            # straggler links correct; no salt (tooling client) means
            # there is nothing coherent to sketch, so the frame is
            # ignored and the asker's stall fallback covers it.
            if peer.recon_salt is not None:
                full, remote_size = body
                window = peer.recon_window
                window.update(peer.recon_pending)
                peer.recon_pending.clear()
                if full:
                    for txid in self.mempool.txids():
                        window.setdefault(
                            reconcile.short_id(peer.recon_salt, txid), txid
                        )
                peer.recon_window_full = full
                cap = reconcile.estimate_capacity(len(window), remote_size)
                self.metrics.recon_sketches_served += 1
                await self._send_guarded(
                    peer,
                    protocol.encode_sketch(
                        len(window), reconcile.sketch(window, cap)
                    ),
                )
        elif mtype is MsgType.SKETCH:
            # Initiator half: XOR our round's sketch against the peer's
            # at ITS capacity and decode the symmetric difference.
            # Admission-exempt but self-guarding: without a round in
            # flight the frame is unsolicited and ignored.
            if peer.recon_inflight_since is not None:
                _remote_size, sk = body
                ours = reconcile.sketch(
                    peer.recon_round, reconcile.capacity_of(sk)
                )
                diff = reconcile.decode(reconcile.combine(ours, sk))
                if diff is None:
                    await self._recon_fallback(peer)
                else:
                    await self._recon_close(peer, diff)
        elif mtype is MsgType.RECONCILDIFF:
            # The initiator closed the round.  Success carries the WHOLE
            # symmetric difference as an announcement: ids we recognize
            # are ours (the peer will GETTX them — the window stays
            # alive as the serve station), ids we don't are the peer's
            # (book them; our next heartbeat GETTXs whatever no other
            # link delivered first).  Failure floods the window (every
            # queued tx still propagates, at flood cost) — except for a
            # full-pool round, where the initiator's classic-paging
            # fallback pulls what it needs instead of us flooding a
            # whole pool.
            success, sids = body
            window = peer.recon_window
            peer.recon_window = {}
            was_full = peer.recon_window_full
            peer.recon_window_full = False
            if success:
                # The window becomes the serve station for the peer's
                # deferred GETTX; ids we recognize nowhere are the
                # peer's half of the diff.  "Nowhere" must include the
                # round we just retired (``recon_served`` before the
                # swap): a tx consumed into the previous round lives in
                # no other per-link structure, and booking it would
                # fetch a copy we already hold.
                served = peer.recon_served
                peer.recon_served = window
                for sid in sids:
                    if (
                        sid not in window
                        and sid not in peer.recon_pending
                        and sid not in served
                    ):
                        peer.recon_expect.add(sid)
            elif not was_full:
                for txid in window.values():
                    tx = self.mempool.get(txid)
                    if tx is not None:
                        await self._gossip_peers(
                            [peer], protocol.encode_tx(tx)
                        )
        elif mtype is MsgType.GETTX:
            # Explicit fetch of short ids a RECONCILDIFF promised.  The
            # window/queue resolve most; the rest fall to a BOUNDED pool
            # scan (the short id is salted per link, so there is no
            # precomputed index — the cap prices a hostile GETTX spray).
            if peer.recon_salt is not None:
                lookup = dict(peer.recon_served)
                lookup.update(peer.recon_window)
                lookup.update(peer.recon_pending)
                missing = {sid for sid in body if sid not in lookup}
                if missing:
                    for n, txid in enumerate(self.mempool.txids()):
                        if not missing or n >= RECON_GETTX_SCAN_MAX:
                            break
                        sid = reconcile.short_id(peer.recon_salt, txid)
                        if sid in missing:
                            missing.discard(sid)
                            lookup[sid] = txid
                for sid in body:
                    tx = self.mempool.get(lookup.get(sid, b""))
                    if tx is not None:
                        # The one place reconciled txs cross the wire:
                        # every push is an explicit fetch of something
                        # the peer verified it still lacks.
                        self.metrics.txs_reconciled += 1
                        await self._gossip_peers([peer], protocol.encode_tx(tx))
        elif mtype is MsgType.HELLO:
            pass  # late HELLO: ignore
        if query_t0 is not None:
            self._h_query.observe(clk() - query_t0)

    def _proof_payload(self, txid: bytes) -> bytes:
        """The wire PROOF reply for ``txid``, through the chain's proof
        cache: the serialized payload (tip zeroed) is memoized on the
        cache entry on first serve, so repeats cost one dict lookup and
        a 4-byte tip patch — the verify-once economics of the sigcache
        applied to the proof path."""
        entry = self.chain.tx_proof_entry(txid)
        if entry is None:
            return protocol.encode_proof(None)
        if entry.payload is None:
            self.chain.proof_cache.note_payload(
                entry, protocol.encode_proof(entry.proof)
            )
        self.metrics.proofs_served += 1
        return protocol.patch_proof_tip(entry.payload, self.chain.height)

    async def _send_guarded(self, peer: _Peer, payload: bytes) -> None:
        """Reply/continuation send with a timeout: a peer that stops
        reading while we block in drain() must not wedge the dispatch
        loop.  Without this, two peers answering each other's sync
        requests with multi-MB replies can fill both transport buffers
        and deadlock — a stalled peer is dropped instead.

        The timeout scales with payload size (ADVICE r3): a flat 5 s on an
        8 MB sync reply would drop every healthy peer on a link slower
        than ~1.6 MB/s and livelock its initial sync through the reconnect
        loop.  The floor stays at GOSSIP_SEND_TIMEOUT_S for small pushes;
        big replies get 1 s per 100 KB — still far faster than any link
        worth keeping, but tolerant of a slow-but-live one.

        Write-queue squat guard (node/governor.py): a peer that keeps
        ASKING while never READING grows our transport buffer without
        ever tripping the send timeout (each send returns once the data
        is buffered).  Past the hard cap the peer is disconnected — the
        replies it refused to read are re-fetchable, the memory is
        not."""
        transport = peer.writer.transport if peer.writer is not None else None
        if (
            transport is not None
            and transport.get_write_buffer_size() > self.governor.write_queue_max
        ):
            self.governor.peers_dropped_squat += 1
            self.log.warning(
                "write queue for %s over %d bytes — dropping the "
                "squatting peer",
                peer.label,
                self.governor.write_queue_max,
            )
            peer.writer.close()  # reader loop will reap it
            return
        timeout = GOSSIP_SEND_TIMEOUT_S + len(payload) / 100_000
        try:
            await asyncio.wait_for(peer.send(payload), timeout=timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            peer.writer.close()  # reader loop will reap it

    async def _gossip(self, payload: bytes, skip: _Peer | None = None) -> int:
        """Send to all peers concurrently; a stalled peer times out and is
        dropped instead of blocking propagation (and the mining loop).
        Returns the number of peers targeted (metrics accounting).

        Best-effort sends additionally skip peers already sitting on
        megabytes of unread replies (the soft write-queue bound): there
        is no point queuing a push behind a backlog, and the skipped
        peer heals through ordinary locator sync."""
        return await self._gossip_peers(
            [p for p in self._peers.values() if p is not skip], payload
        )

    async def _gossip_peers(self, peers, payload: bytes) -> int:
        """The shared fan-out half of ``_gossip``: apply the write-queue
        back-pressure skip to an explicit peer list and send to the
        survivors concurrently.  The reconciliation relay reuses it to
        flood a SUBSET of peers (the flood spine, fallback floods)."""
        targets = []
        for p in peers:
            transport = p.writer.transport
            if (
                transport is not None
                and transport.get_write_buffer_size() > WRITE_QUEUE_GOSSIP_MAX
            ):
                self.governor.write_queue_drops += 1
                continue
            targets.append(p)
        if targets:
            await asyncio.gather(
                *(self._send_guarded(p, payload) for p in targets)
            )
        return len(targets)

    # -- set-reconciliation tx relay (round 23, Erlay analog) ------------
    #
    # Flooding ships every tx to every link: per-node relay bandwidth
    # grows with the CONNECTIVITY of the mesh, not its size.  The recon
    # plane replaces most of that with per-link set reconciliation
    # (node/reconcile.py): announcements queue per link as 4-byte short
    # ids, and a periodic sketch exchange transfers only the symmetric
    # DIFFERENCE of the two queues — O(what the peer is missing), no
    # matter how much the sets overlap.  A small flood spine
    # (``recon_flood_degree`` outbound links per node) keeps worst-case
    # latency at flood speed; reconciliation sweeps the remaining links.
    # Flood stays the universal fallback — decode failure, stalled
    # responder, demoted or pre-RECONCILE peer all degrade to exactly
    # the pre-round-23 behavior, so reconciliation is only ever an
    # optimisation, never a liveness dependency.

    def _recon_enabled(self) -> bool:
        """Whether THIS node queues txs for reconciliation and initiates
        rounds.  ``config.recon_gossip`` is the operator switch; a
        "txrecon" version-bits deployment (when the table carries one)
        additionally gates on miner-signalled activation, so a mixed-
        version mesh upgrades by signal with flood as the shared
        dialect throughout (PR 17's evolution contract).  Recon-off
        nodes still ANSWER REQRECON — serving a sketch of what we have
        costs one small frame and keeps straggler links correct."""
        if not self.config.recon_gossip:
            return False
        dep = self._recon_deployment
        if dep is None:
            return True
        return (
            self.versionbits.state_for_next(
                self.chain, self.chain.tip_hash, dep
            )
            is VBState.ACTIVE
        )

    def _recon_peer_active(self, peer: _Peer, now: float) -> bool:
        """Is this link on the reconciliation plane right now?  Needs a
        pairwise salt (real node, handshake done) and no standing
        demotion."""
        return (
            peer.recon_salt is not None
            and peer.is_node
            and peer.recon_demoted_until <= now
        )

    def _recon_fail(self, peer: _Peer) -> None:
        """Count one failed/stalled round; demote the link to plain
        flooding after RECON_DEMOTE_FAILURES in a row.  Demotion is the
        anti-poisoning story: a peer serving garbage sketches (or none)
        costs us a few wasted frames and then only ITS link's
        efficiency — honest relay continues via flood regardless."""
        peer.recon_failures += 1
        self.metrics.recon_fallbacks += 1
        if peer.recon_failures >= RECON_DEMOTE_FAILURES:
            peer.recon_failures = 0
            peer.recon_demoted_until = self.clock.monotonic() + RECON_DEMOTE_S
            peer.sync_demerits += 1
            self.metrics.recon_demotions += 1
            self.log.warning(
                "peer %s demoted off recon plane for %.0fs",
                peer.label,
                RECON_DEMOTE_S,
            )

    async def _relay_tx(
        self, tx: Transaction, txid: bytes, skip: _Peer | None = None
    ) -> None:
        """Relay one accepted tx: flood the spine, queue the rest.

        Per link, in ``_peers`` insertion order: peers off the recon
        plane are flooded exactly as before; the first
        ``recon_flood_degree`` OUTBOUND and first ``recon_flood_degree``
        INBOUND recon links also get the flood push — the low-latency
        spine, symmetric on purpose: with the dial-earlier topologies
        this repo builds, an outbound-only spine is a DAG pointing at
        the oldest nodes and a tx could only climb back against it at
        reconciliation cadence.  An attacker occupying an inbound spine
        slot merely RECEIVES txs early (it controls nothing about our
        relay to anyone else).  Every other recon link gets the tx
        queued as a short id for its next reconciliation round.  Queue
        overflow floods the oldest entry instead of dropping it — flood
        is the pressure valve, reconciliation the optimisation, never
        the reverse."""
        payload = protocol.encode_tx(tx)
        if not self._recon_enabled():
            await self._gossip(payload, skip=skip)
            return
        now = self.clock.monotonic()
        flood = []
        spine_out = spine_in = max(0, self.config.recon_flood_degree)
        for p in self._peers.values():
            if p is skip:
                continue
            if not self._recon_peer_active(p, now):
                flood.append(p)
                continue
            if p.dial_addr is not None and spine_out > 0:
                spine_out -= 1
                flood.append(p)
                continue
            if p.dial_addr is None and spine_in > 0:
                spine_in -= 1
                flood.append(p)
                continue
            p.recon_pending[reconcile.short_id(p.recon_salt, txid)] = txid
            while len(p.recon_pending) > RECON_PENDING_MAX:
                old_sid = next(iter(p.recon_pending))
                old = self.mempool.get(p.recon_pending.pop(old_sid))
                if old is not None:
                    await self._gossip_peers([p], protocol.encode_tx(old))
        await self._gossip_peers(flood, payload)

    async def _recon_loop(self) -> None:
        """The reconciliation heartbeat: every ``recon_interval_s``, age
        out silent rounds, chase promised-but-undelivered txs (GETTX),
        run any queued full-pool sync round, then initiate ONE steady-
        state round, round-robin over outbound recon links.  One
        initiation per tick, not a thundering herd — with every node
        ticking, each link still reconciles once per interval on
        average, from whichever end dialed it."""
        while self._running:
            await asyncio.sleep(self.config.recon_interval_s)
            try:
                await self._recon_tick()
            except Exception:
                # Heartbeat must survive one bad tick: flood fallback
                # keeps relay correct even if reconciliation is wedged.
                self.log.exception("recon tick failed")

    async def _recon_tick(self) -> None:
        now = self.clock.monotonic()
        # Stall deadline for a round in flight.  Self-supervised HERE
        # (not in the supervision loop) so the plane ages out silent
        # responders even when sync supervision is disabled; a few
        # intervals of slack tolerates a slow link, the send-timeout
        # floor tolerates a long tick.
        # Twice the send timeout, not equal to it: a round's SKETCH can
        # legitimately serialize behind a congested uplink for several
        # seconds, and aging it out at the first opportunity turns
        # congestion into demotions into MORE flooding (measured in the
        # relay-budget A/B before this slack was added).
        stall_s = max(
            8 * self.config.recon_interval_s, 2 * GOSSIP_SEND_TIMEOUT_S
        )
        chase = []
        for p in list(self._peers.values()):
            if (
                p.recon_inflight_since is not None
                and now - p.recon_inflight_since > stall_s
            ):
                await self._recon_fallback(p)
            if p.recon_expect and p.recon_inflight_since is None:
                chase.append(p)
            if (
                p.recon_full_pending
                and p.recon_inflight_since is None
                and self._recon_peer_active(p, now)
            ):
                p.recon_full_pending = False
                await self._recon_start(p, full=True)
        if chase:
            # Announced-but-undelivered short ids: fetch explicitly,
            # once, from ONE link per tick (round-robin).  The pacing is
            # load-bearing, not politeness: during a propagation wave
            # several links announce the SAME tx within one interval,
            # and chasing them all in the same tick fetches that tx once
            # per link.  Serialized, the first fetch lands before the
            # next link's turn and ``_handle_tx``'s cross-link discard
            # cancels the rest (measured: same-tick chasing re-bought a
            # 2.4x duplicate-delivery factor the diff announcements had
            # just eliminated).  The set is cleared either way, so a
            # peer that never answers GETTX can't grow state or wedge
            # anything.
            self._recon_chase_rotate = (
                self._recon_chase_rotate + 1
            ) % len(chase)
            p = chase[self._recon_chase_rotate]
            sids = sorted(p.recon_expect)[: protocol.MAX_RECON_IDS]
            p.recon_expect.clear()
            await self._send_guarded(p, protocol.encode_gettx(sids))
        if not self._recon_enabled() or self.governor.shedding:
            # Under shed pressure the tx plane is already being dropped
            # at admission; initiating new rounds would only add load.
            return
        # Initiate even with an empty local queue: the responder's queue
        # for THIS link rides the same round (its pending freezes into
        # the sketch window, and the decoded diff books it as "theirs"),
        # so the dialing side's heartbeat is what drains BOTH
        # directions.  An idle-link round costs ~30 bytes total.
        candidates = [
            p
            for p in self._peers.values()
            if p.dial_addr is not None
            and p.recon_inflight_since is None
            and self._recon_peer_active(p, now)
        ]
        if candidates:
            self._recon_rotate = (self._recon_rotate + 1) % len(candidates)
            await self._recon_start(candidates[self._recon_rotate], full=False)

    async def _recon_start(self, peer: _Peer, full: bool) -> None:
        """Freeze this link's queue into a round and request a sketch.
        A full round (initial pool sync) additionally folds our whole
        pool in, so the decoded difference is exactly the symmetric
        difference of the two mempools."""
        peer.recon_round = dict(peer.recon_pending)
        peer.recon_pending.clear()
        if full:
            for txid in self.mempool.txids():
                peer.recon_round.setdefault(
                    reconcile.short_id(peer.recon_salt, txid), txid
                )
        peer.recon_round_full = full
        peer.recon_inflight_since = self.clock.monotonic()
        self.metrics.recon_rounds += 1
        await self._send_guarded(
            peer, protocol.encode_reqrecon(len(peer.recon_round), full=full)
        )

    async def _recon_close(self, peer: _Peer, diff) -> None:
        """Successful decode on the initiator: announce the WHOLE
        symmetric difference with RECONCILDIFF and book the half we
        lack as expected.

        Nobody pushes transactions here — that is the round-23 dedup
        that made the byte budget real.  Each end books the diff ids it
        doesn't recognize and fetches them with GETTX one heartbeat
        LATER; a copy arriving from any other link in that window
        cancels the fetch (``_handle_tx`` discards the id under every
        link salt), so a tx crossing a well-connected mesh is sent to
        each node once, not once per racing link.  An id costs 4 bytes
        where an eager duplicate push costs a whole transaction —
        measured in the relay-budget A/B, eager pushing tripled the
        recon arm's bytes."""
        round_, was_full = peer.recon_round, peer.recon_round_full
        peer.recon_round = {}
        peer.recon_round_full = False
        peer.recon_inflight_since = None
        peer.recon_failures = 0
        self.metrics.recon_success += 1
        # The decoded diff is ≤ sketch capacity (64), comfortably inside
        # the frame's id cap.
        await self._send_guarded(
            peer, protocol.encode_recondiff(True, tuple(diff))
        )
        # Book only ids we recognize NOWHERE on this link.  The frozen
        # round alone is not enough: a tx that arrived (from any link)
        # after the freeze sits in this link's pending queue, shows up
        # in the diff as "missing from the round", and booking it would
        # fetch a copy we already hold — the arrival can't have
        # cancelled a booking that didn't exist yet.
        peer.recon_expect.update(
            sid
            for sid in diff
            if sid not in round_
            and sid not in peer.recon_pending
            and sid not in peer.recon_window
            and sid not in peer.recon_served
        )
        # Our half stays fetchable: the round becomes the serve station
        # the peer's deferred GETTX resolves from, without a pool scan.
        peer.recon_served = round_
        if was_full:
            # The supervised initial sync completed over the recon plane.
            peer.mempool_inflight_since = None

    async def _recon_fallback(self, peer: _Peer) -> None:
        """Failed round on the initiator side (undecodable sketch or a
        silent responder): tell the responder (best effort), degrade
        THIS round to the pre-recon behavior — flood what it carried,
        or classic cursor paging for a full-pool sync (flooding a whole
        pool is exactly what reconciliation exists to avoid) — and
        count toward demotion."""
        round_, was_full = peer.recon_round, peer.recon_round_full
        peer.recon_round = {}
        peer.recon_round_full = False
        peer.recon_inflight_since = None
        self._recon_fail(peer)
        await self._send_guarded(peer, protocol.encode_recondiff(False))
        if was_full:
            peer.mempool_inflight_since = self.clock.monotonic()
            await self._send_guarded(peer, protocol.encode_getmempool(None))
            return
        for txid in round_.values():
            tx = self.mempool.get(txid)
            if tx is not None:
                await self._gossip_peers([peer], protocol.encode_tx(tx))

    # -- chain/mempool handlers -----------------------------------------

    def _block_gossip_payload(self, block: Block) -> tuple[bytes, int]:
        """Choose the push encoding: compact when there are transactions
        worth eliding (the receiver's mempool should hold them), full
        BLOCK otherwise (an empty/coinbase-only block has nothing to
        elide, and the full form needs no round trip ever; a >u16-tx
        block exceeds the compact form's counts).  Returns (payload,
        bytes saved per delivered peer) — the CALLER accounts metrics
        once it knows how many peers actually received it."""
        now = self.clock.wall()
        full = protocol.encode_block(block, sent_ts=now)
        if self.config.compact_gossip and 1 < len(block.txs) <= 0xFFFF:
            compact = protocol.encode_cblock(block, sent_ts=now)
            return compact, len(full) - len(compact)
        return full, 0

    async def _handle_cblock(
        self, cb: protocol.CompactBlock, peer: _Peer
    ) -> None:
        """Reconstruct a compact block from the mempool; fetch the rest.

        Order of operations is the DoS story: the header must carry proof
        of work at the EXACT difficulty consensus requires of its parent
        (``Chain.required_difficulty`` — contextual, so this holds on
        retargeting chains too) before any state is touched or any request
        sent; parking a pending reconstruction or triggering a GETBLOCKTXN
        round trip therefore costs a real block's worth of work.  A
        compact push whose parent we don't know can't be priced — it falls
        straight to locator sync, which an out-of-order arrival needs
        anyway.  Txids are full SHA-256d hashes, so mempool hits are
        byte-exact by construction and full consensus validation still
        runs in ``_handle_block``.
        """
        from p1_tpu.core.header import meets_target

        header = cb.header
        bhash = header.block_hash()
        if self._store_degraded:
            # Serve-only: don't spend a GETBLOCKTXN round trip on a
            # block the door will refuse; recovery re-fetches it.
            self.metrics.store_blocks_deferred += 1
            return
        if bhash in self.chain or (bhash, peer) in self._pending_cblocks:
            return  # duplicate push
        expected = self.chain.required_difficulty(header.prev_hash)
        if expected is None:
            await self._request_blocks(peer)
            return
        if header.difficulty != expected or not meets_target(
            bhash, header.difficulty
        ):
            self.metrics.blocks_rejected += 1
            self.log.warning("rejected compact block from %s: bad work", peer.label)
            return
        self.metrics.cblocks_received += 1
        txs: list = [None] * cb.ntx
        for i, tx in cb.prefilled:
            txs[i] = tx
        rest = [i for i in range(cb.ntx) if txs[i] is None]
        want: dict[int, bytes] = {}
        for i, txid in zip(rest, cb.txids):
            tx = self.mempool.get(txid)
            if tx is not None:
                txs[i] = tx
                self.metrics.cblock_tx_hits += 1
            else:
                want[i] = txid
        if not want:
            await self._handle_block(
                Block(header, tuple(txs)), origin=peer, sent_ts=cb.sent_ts
            )
            return
        held = sum(1 for (_h, p) in self._pending_cblocks if p is peer)
        if held >= PENDING_CBLOCKS_PER_PEER:
            # One peer must not squat the reconstruction table: each slot
            # pins a partially rebuilt block in RAM until the deadline
            # reaps it.  The block is real (it passed the work gate), so
            # locator sync recovers it — refusing the slot loses nothing.
            self.governor.cblock_slot_drops += 1
            return
        self._pending_cblocks[(bhash, peer)] = _PendingCompact(
            header, txs, want, cb.sent_ts, asked_at=self.clock.monotonic()
        )
        while len(self._pending_cblocks) > MAX_PENDING_CBLOCKS:
            self._pending_cblocks.popitem(last=False)
        await self._send_guarded(
            peer, protocol.encode_getblocktxn(bhash, sorted(want))
        )

    async def _handle_blocktxn(self, body, peer: _Peer) -> None:
        bhash, txs = body
        # Keyed by (hash, peer): an unsolicited BLOCKTXN from a peer we
        # never asked resolves nothing and cannot destroy a reconstruction
        # in flight with the peer we DID ask.
        pending = self._pending_cblocks.pop((bhash, peer), None)
        if pending is None:
            return  # answered twice / evicted meanwhile / never asked
        indices = sorted(pending.want)
        if len(txs) != len(indices):
            self.log.warning("BLOCKTXN wrong count from %s", peer.label)
            return
        for i, tx in zip(indices, txs):
            if tx.txid() != pending.want[i]:
                # The reply does not match the advertised block — drop the
                # reconstruction; the chain heals via sync if it was real.
                self.log.warning("BLOCKTXN txid mismatch from %s", peer.label)
                return
            pending.txs[i] = tx
        self.metrics.cblock_tx_fetched += len(indices)
        await self._handle_block(
            Block(pending.header, tuple(pending.txs)),
            origin=peer,
            sent_ts=pending.sent_ts,
        )

    async def _handle_block(
        self,
        block: Block,
        origin: _Peer | None = None,
        gossip: bool = True,
        sent_ts: float | None = None,
    ):
        if self._store_degraded and block.block_hash() not in self.chain:
            # Degraded serve-only mode: a block we cannot persist is a
            # block we must not acknowledge — accepting it would let the
            # in-memory chain run ahead of a disk that will lose it.
            # Peers keep it; recovery re-fetches via locator sync.
            self.metrics.store_blocks_deferred += 1
            return AddResult(
                AddStatus.REJECTED, reason="store degraded: serve-only mode"
            )
        # Zero-repack pipeline: a block decoded off the wire carries its
        # exact frame bytes in its encoding cache (core/block.py), so the
        # hashing below (add_block's validation), the store append, and
        # the re-relay encode all reuse them — the block is packed at
        # most once per process lifetime (docs/PERF.md "host ingest
        # plane").  Only mempool-reconstructed compact blocks serialize
        # fresh, once, on first use (their full frame never arrived).
        clk = self._tel_clock
        t0 = clk() if clk is not None else 0.0
        if block.block_hash() not in self.chain:
            # VALIDATE stage: batch-verify the block's transfer
            # signatures into the verify-once cache on the pipeline's
            # validate lane BEFORE the connect — add_block's check_block
            # then hits the cache, so the Ed25519 cost (the old stage
            # table's dominant term) is paid off-loop when staging is
            # on.  Cache-warmer only: outcomes are check_block's alone,
            # and a hostile invalid-signature block just pays its
            # (bounded, ban-scored) verify at connect time instead.
            await self.pipeline.run_validate(
                preverify_signatures,
                block.txs,
                self.chain.genesis.block_hash(),
                self.sig_cache,
                nbytes=len(block.serialize()),
            )
        res = self.chain.add_block(block)
        if clk is not None:
            self._h_validate.observe(clk() - t0)
        if res.status is AddStatus.ACCEPTED:
            # Any accepted block is catch-up progress no matter who
            # served it: the supervised sync's deadline and attempt
            # budget reset (supervision.py — the honest-slow guarantee).
            self._sync.progress()
            if gossip and getattr(origin, "budget", None) is not None:
                # A pushed block that connected as NEW earns its charge
                # back (governor.py): PoW makes new blocks self-limiting,
                # so the blocks budget only ever throttles duplicates,
                # stale relays, and orphan spray — an honest miner can
                # never exhaust it, however fast the mesh mines.  Batch
                # sync replies (gossip=False) were never charged.
                origin.budget.refund(CLASS_BLOCKS)
            if sent_ts:
                # Push-gossip propagation delay (send -> accept), recorded
                # only for blocks that actually connected: duplicates and
                # orphans would skew the figure toward re-delivery noise.
                # Falsy covers both "no stamp" spellings — None (never
                # passed a stamp) and the codec's 0.0 "no stamp" encode
                # (protocol.encode_block) — so an unstamped tooling push
                # can't record a nonsense epoch-sized delay.
                prop_delay = max(0.0, self.clock.wall() - sent_ts)
                self.metrics.propagation_delays_s.append(prop_delay)
                # Histogram twin of the raw window: virtual-time under
                # the sim, so scenarios assert p95 propagation bounds.
                self.telemetry.observe("block.propagation_s", prop_delay)
            self.metrics.blocks_accepted += 1
            # incl. cascaded orphans; a failing disk degrades, never
            # unwinds this handler (_store_append).
            t0 = clk() if clk is not None else 0.0
            await self._store_append(res.connected)
            if clk is not None:
                self._h_store.observe(clk() - t0)
            for b in res.connected:
                # Serving plane: build each connected block's compact
                # filter while its body is hot (incremental-at-connect;
                # anything LRU-evicted later rebuilds from the store).
                self.chain.filter_index.add_block(b)
            # Push plane: notify live subscriptions of the connect (the
            # no-subscriber case is a cursor fast-forward, not a build).
            await self.subscriptions.notify()
            if res.tip_changed:
                if not gossip:
                    # Batch-synced tip movement: queue the one-shot
                    # announce for when the episode quiesces.
                    self._announce_tip = True
                if res.removed:
                    self.metrics.reorgs += 1
                self.mempool.apply_block_delta(res.removed, res.added)
                self._abort_inflight_search()
                tip = self.chain.tip
                self.log.info(
                    "tip height=%d hash=%s nonce=%d txs=%d reorg=%d source=%s",
                    self.chain.height,
                    tip.block_hash().hex()[:16],
                    tip.header.nonce,
                    len(tip.txs),
                    len(res.removed),
                    origin.label if origin else "local",
                )
            if gossip:
                # Relay-fan-out stage span: encode + the concurrent send
                # round (awaits included — the figure is what a tip push
                # costs this event loop end to end).
                t0 = clk() if clk is not None else 0.0
                payload, saved_per_peer = self._block_gossip_payload(block)
                n = await self._gossip(payload, skip=origin)
                if clk is not None:
                    self._h_relay.observe(clk() - t0)
                if saved_per_peer and n:
                    # Per delivered peer: each would otherwise have
                    # received the full BLOCK frame.
                    self.metrics.cblocks_sent += n
                    self.metrics.cblock_bytes_saved += saved_per_peer * n
        elif res.status is AddStatus.ORPHAN and origin is not None:
            await self._request_blocks(origin)
        elif res.status is AddStatus.REJECTED:
            self.metrics.blocks_rejected += 1
            self.log.warning(
                "rejected block from %s: %s",
                origin.label if origin else "local",
                res.reason,
            )
        return res

    async def _handle_tx(self, tx: Transaction, origin: _Peer | None = None) -> None:
        if self.mempool.add(tx):
            self.metrics.txs_accepted += 1
            txid = tx.txid()
            # Arrival stamp for the propagation budget (bounded: drop
            # the oldest entry like a poor man's deque-of-dict).
            if len(self.tx_seen_at) >= 8192:
                self.tx_seen_at.pop(next(iter(self.tx_seen_at)))
            self.tx_seen_at[txid] = self.clock.monotonic()
            # A delivered tx settles every link's RECONCILDIFF IOU for
            # it, not just the origin's: other links may have announced
            # the same tx in their own diffs, and discarding it here —
            # under each link's own salt — is what turns racing
            # announcements into ONE delivery instead of one per link
            # (the round-23 dedup; eager cross-link pushes measured 3x
            # the bytes).
            for p in self._peers.values():
                if p.recon_expect and p.recon_salt is not None:
                    p.recon_expect.discard(
                        reconcile.short_id(p.recon_salt, txid)
                    )
            await self._relay_tx(tx, txid, skip=origin)

    async def submit_tx(self, tx: Transaction) -> None:
        """Local API: inject a transaction (CLI/tests)."""
        await self._handle_tx(tx, origin=None)

    async def request_sync(self) -> None:
        """Ask every peer for blocks past our locator.  Used at quiesce: a
        push dropped in the final instant (send timeout, reconnect window)
        leaves no descendant to trigger an orphan backfill, so tips could
        stay split on a same-height tie without this pull."""
        if self._store_degraded:
            return  # serve-only: don't solicit blocks we would refuse
        if self._peers:
            await self._gossip(protocol.encode_getblocks(self.chain.locator()))

    # -- mining ----------------------------------------------------------

    def _abort_inflight_search(self) -> None:
        if self._abort is not None:
            self._abort.set()

    def _mining_parent(self) -> Block:
        """The block this miner chooses to extend.  Normally the tip —
        but MINING POLICY (not consensus: the DAG's validity rules stay
        wall-clock-free) refuses to extend a block stamped more than
        ``ANCHOR_SLACK_S`` past local wall time.  The height-1
        bootstrap-anchor exemption (core/retarget.py) means a hostile
        first miner CAN stamp decades ahead and validly poison the
        chain clock — every later honest stamp would crawl at parent+1,
        spans would read seconds, and difficulty would ratchet toward
        255 until the chain stalls.  This guard is how the honest
        majority responds: their miners build from the heaviest
        sanely-stamped block instead, out-working and orphaning the
        poisoned suffix.  Wall time influences only which branch THIS
        miner grows, never what any node accepts — replay determinism
        holds.

        The slack is deliberately enormous compared to the consensus
        cap: honest chains legitimately run their clock ahead of wall
        time during mining bursts (strict increase forces +1 s stamps
        at any block rate, so a 5k-block soak sits ~1.4 h "in the
        future"; an early too-tight bound of now + max_increment wedged
        real nodes at height ~33, hot-looping one candidate).  Only an
        anchor-style jump — months-to-decades, impossible to reach by
        +1 s crawling at any realistic block count — trips it.
        """
        tip = self.chain.tip
        if self.chain.retarget is None:
            return tip
        bound = int(self.clock.wall()) + ANCHOR_SLACK_S
        if tip.header.timestamp <= bound:
            return tip
        return self.chain.best_block_within(bound)

    def _assemble(self) -> Block:
        parent = self._mining_parent()
        on_tip = parent.block_hash() == self.chain.tip_hash
        height = self.chain.height_of(parent.block_hash()) + 1
        coinbase = Transaction.coinbase(self.miner_id, height)
        if on_tip:
            txs = (
                coinbase,
                *self.mempool.select(max(0, self.config.max_block_txs - 1)),
            )
        else:
            # Policy fork off a poisoned suffix: pool selection is only
            # guaranteed connectable against the TIP's ledger, so carry
            # the coinbase alone until the honest branch takes over.
            txs = (coinbase,)
        ts = max(parent.header.timestamp + 1, int(self.clock.wall()))
        if self.chain.retarget is not None:
            # The shared clamp: largest consensus-valid stamp (strict
            # increase; forward cap from height 2 — a runaway local
            # clock must not assemble a block every peer rejects).
            ts = self.chain.retarget.clamp_timestamp(
                height - 1, parent.header.timestamp, ts
            )
        header = BlockHeader(
            # Version-bits signaling (round 20): top-bits + every
            # deployment bit worth signaling on this parent, or the
            # legacy literal 1 when no deployments are configured.
            version=self.versionbits.mining_version(
                self.chain, parent.block_hash()
            ),
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=ts,
            # What consensus requires of the next block — equals the
            # configured difficulty unless a retarget rule has moved it.
            difficulty=self.chain.required_difficulty(parent.block_hash()),
            nonce=0,
        )
        return Block(header, txs)

    async def _mine_loop(self) -> None:
        try:
            await self._mine_loop_inner()
        except asyncio.CancelledError:
            raise
        except Exception:
            # A silently dead miner looks like a healthy idle node; make
            # the failure loud here — stop_mining() swallows (logs) the
            # re-raise so teardown still completes.
            self.log.exception("mining loop died")
            raise

    async def _mine_loop_inner(self) -> None:
        import threading

        loop = asyncio.get_running_loop()
        while self._running:
            if (
                self._store_degraded
                or self.governor.shedding
                or self.validation_state != VALIDATED
            ):
                # Serve-only / SHED / ASSUMED: a sealed block would be
                # refused at the door (degraded disk), assembled under
                # memory pressure the node is trying to shed, or built
                # on state this node has not yet validated (mining on an
                # assumed tip would WAGER hashpower on a peer's claim) —
                # don't burn the CPU.  Mining resumes when the state
                # clears / the revalidation flips.
                await asyncio.sleep(0.25)
                continue
            candidate = self._assemble()
            self._abort = threading.Event()
            t0 = self.clock.monotonic()
            sealed = await loop.run_in_executor(
                None, self.miner.search_nonce, candidate.header, self._abort
            )
            stats = self.miner.last_stats
            self.metrics.hashes_done += stats.hashes_done
            self.metrics.mine_elapsed_s += stats.elapsed_s
            if sealed is None:
                continue  # aborted: tip moved under us, reassemble
            block = Block(sealed, candidate.txs)
            self.metrics.blocks_mined += 1
            self.metrics.last_block_time_s = self.clock.monotonic() - t0
            self.log.info(
                "mined height=%d nonce=%d txs=%d t=%.3fs hps=%.0f",
                self.chain.height + 1,
                sealed.nonce,
                len(block.txs),
                self.metrics.last_block_time_s,
                stats.hashes_per_sec,
            )
            # Shield the post-seal handling.  add_block + gossip happen
            # inside the _post_seal task, which cancellation of THIS loop
            # cannot kill; the guarantee that the sealed block lands in the
            # chain and reaches peers comes from stop_mining()/stop()
            # awaiting _post_seal, NOT from any ordering within this loop.
            # Without the shield, cancellation between add_block and the
            # gossip send strands the miner one block ahead forever.
            self._post_seal = asyncio.create_task(
                self._handle_block(block, origin=None)
            )
            await asyncio.shield(self._post_seal)
            self._post_seal = None
            await asyncio.sleep(0)  # let gossip/tx handlers breathe

    # -- introspection ---------------------------------------------------

    def peer_count(self) -> int:
        return len(self._peers)

    def telemetry_snapshot(self) -> dict:
        """The METRICS wire payload (`p1 metrics`): the registry dump
        plus just enough identity to label a scrape.  Distinct from
        ``status()`` — that is the curated operator view; this is the
        raw catalog every exporter renders from.

        The validation-backend counters (keys.STATS, round 15) are
        synced into registry gauges HERE, on the export path only:
        they are process-wide accumulators owned by core/keys.py, and
        mirroring them at verify time would put a registry write on the
        hot validation path for a number only scrapes read.  Gauges
        (not counters) because the registry copy is a mirror, not the
        source of truth."""
        for name, value in (
            ("validation.sigs_serial", keys.STATS.serial),
            ("validation.sigs_batched", keys.STATS.batched),
            ("validation.sigs_cached", self.sig_cache.hits),
            *(
                (f"validation.backend.{b}", keys.STATS.backends.get(b, 0))
                for b in keys.SIG_BACKENDS
            ),
        ):
            self.telemetry.gauge(name).value = value
        return {
            "role": "node",
            "miner_id": self.miner_id,
            "height": self.chain.height,
            **self.telemetry.snapshot(),
        }

    def status(self) -> dict:
        """The two BASELINE metrics + node state, JSON-ready."""
        return {
            "miner_id": self.miner_id,
            "height": self.chain.height,
            "tip": self.chain.tip_hash.hex(),
            "peers": self.peer_count(),
            "known_addrs": len(self._known_addrs) + len(self._tried_addrs),
            "banned_hosts": sum(
                1
                for until in self._banned_until.values()
                if until > self.clock.monotonic()
            ),
            "mempool": len(self.mempool),
            "hashes_per_sec": round(self.metrics.hashes_per_sec),
            "time_to_block_s": round(self.metrics.last_block_time_s, 3),
            "blocks_mined": self.metrics.blocks_mined,
            "blocks_accepted": self.metrics.blocks_accepted,
            "reorgs": self.metrics.reorgs,
            "txs_accepted": self.metrics.txs_accepted,
            "propagation": self.metrics.propagation_summary(),
            # Compact block relay effectiveness (BIP152-style gossip).
            "compact": {
                "sent": self.metrics.cblocks_sent,
                "received": self.metrics.cblocks_received,
                "tx_hits": self.metrics.cblock_tx_hits,
                "tx_fetched": self.metrics.cblock_tx_fetched,
                "bytes_saved": self.metrics.cblock_bytes_saved,
            },
            "wire": {
                "bytes_sent": self.metrics.bytes_sent,
                "bytes_received": self.metrics.bytes_received,
                # Per-family relay-byte attribution (round 23): where
                # this node's outbound bandwidth actually went, keyed by
                # _RELAY_ACCOUNTING family ("tx" + "recon" = the relay
                # plane the reconciliation work budgets).
                "relay_bytes": self.metrics.relay_bytes(),
            },
            # Set-reconciliation relay (round 23, node/reconcile.py):
            # round outcomes plus the per-link plane state.
            "recon": {
                "enabled": self._recon_enabled(),
                "rounds": self.metrics.recon_rounds,
                "sketches_served": self.metrics.recon_sketches_served,
                "success": self.metrics.recon_success,
                "fallbacks": self.metrics.recon_fallbacks,
                "demotions": self.metrics.recon_demotions,
                "txs_reconciled": self.metrics.txs_reconciled,
                "active_links": sum(
                    1
                    for p in self._peers.values()
                    if self._recon_peer_active(p, self.clock.monotonic())
                ),
                "pending": sum(
                    len(p.recon_pending) for p in self._peers.values()
                ),
            },
            "liveness": {
                "pings_sent": self.metrics.pings_sent,
                "peers_evicted_idle": self.metrics.peers_evicted_idle,
            },
            # Request supervision: sync-stall detection and failover
            # (node/supervision.py) — how often catch-up was rescued
            # from a non-serving peer.
            "sync": {
                "stalls": self.metrics.sync_stalls,
                "failovers": self.metrics.sync_failovers,
                "demotions": self.metrics.sync_demotions,
                "exhausted": self.metrics.sync_exhausted,
                "cblock_fetch_stalls": self.metrics.cblock_fetch_stalls,
                "mempool_stalls": self.metrics.mempool_sync_stalls,
            },
            # Storage durability: disk health (degraded = serve-only
            # mode after ENOSPC/EIO, recovering under backoff) plus what
            # the store's startup scan had to quarantine or truncate
            # (chain/store.py's v3 checksum framing).
            "storage": {
                "persistent": self.store is not None,
                "degraded": self._store_degraded,
                "errors": self.metrics.store_errors,
                "retries": self.metrics.store_retries,
                "recoveries": self.metrics.store_recoveries,
                "blocks_deferred": self.metrics.store_blocks_deferred,
                "pending_records": len(self._store_pending),
                "last_error": self._store_last_error,
                "healed": dict(self.store.healed)
                if self.store is not None
                else None,
                # Segmented layout + pruned mode (round 18): the
                # wire-visible ``pruned`` posture — a syncing peer
                # reading this knows not to ask us for deep history.
                "segmented": getattr(self.store, "segments", None)
                is not None
                and len(getattr(self.store, "segments", ())) > 0,
                "pruned": {
                    "enabled": self.config.prune_keep_blocks > 0,
                    "keep_blocks": self.config.prune_keep_blocks,
                    "floor": self.chain.prune_floor,
                    "segments_pruned": self.metrics.store_segments_pruned,
                    "refusals": self.metrics.pruned_refusals,
                },
            },
            # Overload resilience (node/governor.py): SHED state +
            # hysteresis over the accounted memory gauge, per-peer
            # admission drops, write-queue enforcement, and the
            # memory-bounded operation telemetry (bodies evicted from
            # the RAM index / refetched on demand from the store).
            "overload": {
                **self.governor.snapshot(),
                "resident_body_bytes": self.chain.resident_body_bytes,
                "bodies_evicted": self.chain.bodies_evicted,
                "body_refetches": self.chain.body_refetches,
                "body_cache_blocks": self.config.body_cache_blocks,
                "mining_paused": self.governor.shedding
                or self._store_degraded
                or self.validation_state != VALIDATED,
            },
            # Staged pipeline (round 19, node/pipeline.py): per-stage
            # queue depths + worker liveness — an operator reading a
            # growing store depth is watching disk back-pressure form
            # before the governor sheds on it.
            "pipeline": {
                **self.pipeline.status(),
                "worker_respawns": self.metrics.worker_respawns,
            },
            # Untrusted snapshot sync (round 12, chain/snapshot.py): the
            # node's trust posture and the snapshot plane's telemetry —
            # an operator reading "assumed" knows every answer is
            # conditioned on a snapshot still being revalidated.
            "snapshot": {
                "state": self.validation_state,
                "base_height": self.chain.base_height,
                "checkpoint_interval": self.chain.checkpoint_interval,
                "checkpoints": len(self.chain.state_checkpoints),
                "fetching": self._snap_fetch is not None,
                "revalidating": self._bg_chain is not None,
                "bg_height": (
                    self._bg_chain.height
                    if self._bg_chain is not None
                    else None
                ),
                "fetches": self.metrics.snapshot_fetches,
                "chunks_served": self.metrics.snapshot_chunks_served,
                "flips": self.metrics.snapshot_flips,
                "divergences": self.metrics.snapshot_divergences,
                "fallbacks": self.metrics.snapshot_fallbacks,
                "stalls": self.metrics.snapshot_stalls,
                "revalidated_blocks": self.metrics.revalidated_blocks,
            },
            # The always-on maintenance plane (round 20): what the node
            # has done to itself while serving — live re-bases, online
            # prune/compact — plus the continuous-snapshot economics
            # (incremental builds vs chunks reused) and the
            # version-bits activation report.
            "maintenance": self.maintenance_report(),
            # Query serving plane (round 9): read-traffic counters (how
            # many proofs/filters this node served and at what cache hit
            # rate) — the host-side view of the tier benchmarks/
            # query_plane.py measures; replica workers (`p1 serve`)
            # report their own copy of this block over GETSTATUS.
            "queries": {
                "proofs_served": self.metrics.proofs_served,
                "filters_served": self.metrics.filters_served,
                "filter_bytes_served": self.metrics.filter_bytes_served,
                "proof_cache": self.chain.proof_cache.snapshot(),
                "filter_cache": self.chain.filter_index.snapshot(),
            },
            # Wallet push plane (round 21, node/subscriptions.py): live
            # watch sessions, the degradation ladder's counters
            # (coalesced/dropped/disconnected — a slow wallet degrades,
            # the write gauge does not balloon), cursor replays, and
            # the filter-header commitment chain's span.
            "subscriptions": {
                **self.subscriptions.snapshot(),
                "filter_headers": len(self.chain.filter_headers),
            },
            # Validation fast lane (round 8): the verify-once signature
            # cache (this node's instance — hits are blocks connecting
            # without re-paying Ed25519 for mempool-resident transfers)
            # plus the process-wide backend accounting (how many
            # signatures went through batch calls vs one-at-a-time, and
            # on which backend).
            "validation": {
                **self.sig_cache.snapshot(),
                "batched": keys.STATS.batched,
                "batches": keys.STATS.batches,
                "serial": keys.STATS.serial,
                "pool_dispatches": keys.STATS.pool_dispatches,
                # backend_label, not backend(): the resolver may probe
                # (and once-compile) the native rung — a GETSTATUS
                # served on the loop must read the memoized name, never
                # be the call that pays that load.
                "backend": keys.backend_label(),
                # Per-backend signature counts (round 15 ladder) — the
                # key set is FIXED (every rung always present, zeros
                # included) so the status wire contract stays
                # byte-pinnable (tests/test_telemetry.py STATUS_KEYS).
                "backends": {
                    name: keys.STATS.backends.get(name, 0)
                    for name in keys.SIG_BACKENDS
                },
                "workers": keys.verify_workers(),
            },
            # Conservation probe: with a coinbase in every block (ours) and
            # fees credited to miners, the ledger must sum to exactly
            # BLOCK_REWARD x height — any double-spend or bad reorg undo
            # breaks this, so `p1 net` audits it across all nodes.
            "ledger_sum": sum(self.chain.balances_snapshot().values()),
        }
