"""Thin p2p client: inject a transaction into a running node.

Capability parity: a usable mempool needs an entry point for transactions
from outside the node process (BASELINE.json:5 names the mempool; without
this, only miners' own processes could ever create payload for blocks).
The client speaks one round of the ordinary peer protocol — HELLO exchange
(validating genesis, i.e. that both sides run the same chain parameters),
then a single TX frame — and disconnects; the receiving node gossips the
transaction onward like any other.
"""

from __future__ import annotations

import asyncio

from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.tx import Transaction
from p1_tpu.node import protocol
from p1_tpu.node.protocol import Hello, MsgType


async def send_tx(
    host: str, port: int, tx: Transaction, difficulty: int, timeout: float = 10.0
) -> int:
    """Push ``tx`` to the node at host:port; return the node's tip height.

    ``difficulty`` selects the chain (it determines the genesis block the
    HELLO handshake validates against); a mismatch raises ValueError
    rather than silently feeding a transaction to the wrong network.
    """

    async def _run() -> int:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            genesis_hash = make_genesis(difficulty).block_hash()
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(genesis_hash, 0, 0))
            )
            mtype, hello = protocol.decode(await protocol.read_frame(reader))
            if mtype is not MsgType.HELLO:
                raise ValueError("node did not HELLO")
            if hello.genesis_hash != genesis_hash:
                raise ValueError(
                    "genesis mismatch: node runs a different chain "
                    "(check --difficulty)"
                )
            await protocol.write_frame(writer, protocol.encode_tx(tx))
            return hello.tip_height
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    return await asyncio.wait_for(_run(), timeout)
