"""Thin p2p client: one-shot wallet/tooling rounds against a running node.

Capability parity: a usable mempool needs an entry point for transactions
from outside the node process (BASELINE.json:5 names the mempool; without
this, only miners' own processes could ever create payload for blocks).
Each client call speaks one round of the ordinary peer protocol — HELLO
exchange (validating genesis, i.e. that both sides run the same chain
parameters), then its one request — and disconnects; the node treats the
client like any short-lived peer.
"""

from __future__ import annotations

import asyncio
import contextlib

from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.tx import Transaction
from p1_tpu.node import protocol
from p1_tpu.node.protocol import Hello, MsgType


@contextlib.asynccontextmanager
async def _session(
    host: str,
    port: int,
    difficulty: int,
    retarget=None,
    handshake_timeout: float | None = None,
    transport=None,
):
    """Connect + HELLO-validate against the chain selected by
    ``difficulty`` (+ optional ``RetargetRule`` — part of chain identity);
    yields (reader, writer, peer_hello).  The ONE copy of the handshake
    all clients share — a protocol change lands here once.

    ``handshake_timeout`` bounds connect + HELLO exchange with its own
    deadline: a half-open peer (accepts TCP, never answers — a dead
    process behind a live listen backlog) must cost a supervised caller
    one stall, not its entire overall timeout.  None keeps the caller's
    outer ``wait_for`` as the only bound (the one-shot clients, whose
    whole round is already a single short timeout).

    ``transport`` is the network seam (node/transport.py): None dials
    real sockets; a simulator handle runs the SAME client code over
    in-memory links under the virtual clock — how the chaos plane puts
    verifying wallets inside its deterministic storms."""

    async def _connect():
        if transport is None:
            reader, writer = await asyncio.open_connection(host, port)
        else:
            reader, writer = await transport.connect(host, port)
        try:
            genesis_hash = make_genesis(difficulty, retarget).block_hash()
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(genesis_hash, 0, 0))
            )
            mtype, hello = protocol.decode(
                await protocol.read_frame(reader)
            )
            if mtype is not MsgType.HELLO:
                raise ValueError("node did not HELLO")
            if hello.genesis_hash != genesis_hash:
                raise ValueError(
                    "genesis mismatch: node runs a different chain "
                    "(check --difficulty / retarget flags)"
                )
            return reader, writer, hello
        except BaseException:
            # Incl. the cancellation a handshake timeout injects: the
            # socket must not outlive the abandoned attempt.
            writer.close()
            raise

    if handshake_timeout is None:
        reader, writer, hello = await _connect()
    else:
        reader, writer, hello = await asyncio.wait_for(
            _connect(), handshake_timeout
        )
    try:
        yield reader, writer, hello
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


async def _read_msg(reader, writer):
    """One decoded frame, transparently answering keepalive PINGs — a
    client mid-round (e.g. a long headers sync between requests) must
    show liveness or the node's idle probe evicts it (node.py)."""
    while True:
        mtype, body = protocol.decode(await protocol.read_frame(reader))
        if mtype is MsgType.PING:
            await protocol.write_frame(writer, protocol.encode_pong(body))
            continue
        return mtype, body


async def send_tx(
    host: str,
    port: int,
    tx: Transaction,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> int:
    """Push ``tx`` to the node at host:port; return the node's tip height.

    ``difficulty`` selects the chain (it determines the genesis block the
    HELLO handshake validates against); a mismatch raises ValueError
    rather than silently feeding a transaction to the wrong network.
    """

    async def _run() -> int:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            hello,
        ):
            await protocol.write_frame(writer, protocol.encode_tx(tx))
            return hello.tip_height

    return await asyncio.wait_for(_run(), timeout)


async def get_proof(
    host: str,
    port: int,
    txid: bytes,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
):
    """Fetch the SPV inclusion proof for ``txid`` from the node at
    host:port.  Returns a ``TxProof`` or ``None`` (not confirmed on the
    node's main chain).  The caller verifies the proof itself with
    ``p1_tpu.chain.verify_tx_proof`` — never trust, always check."""

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getproof(txid))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.PROOF:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_headers(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 60.0,
    retarget=None,
    max_headers: int = 1_000_000,
    stall_timeout_s: float = 15.0,
    fallback_peers=(),
    attempts_max: int = 4,
):
    """Headers-first light-client sync: the node's full main-chain header
    list, genesis-first, ~80 B per block.  Fetches until a reply adds
    nothing new; the CALLER must then verify the chain itself with
    ``p1_tpu.chain.replay_host`` (PoW, linkage, difficulty schedule) —
    this function moves bytes, it does not bless them.  ``max_headers``
    bounds memory against a responder that streams garbage forever.

    Supervised (node/supervision.py, the same layer the node runs over
    its own locator sync): each GETHEADERS round must land a reply that
    grows the chain within ``stall_timeout_s``, or the session is
    abandoned and the fetch resumes — accumulated headers kept — against
    the next address in ``[primary, *fallback_peers]`` after a jittered
    backoff.  The locator is rebuilt from what we already hold, so a
    failover re-fetches at most one batch, and the link-point truncation
    below already handles a fallback peer on a different (heavier-tip)
    branch.  ``attempts_max`` consecutive stalls raise ``SyncStalled``;
    progress resets the budget, so an honest-slow peer that keeps
    serving batches is never abandoned.  Protocol violations (unlinked
    or non-contiguous batches) still raise ``ValueError`` immediately —
    a lying peer is not retried, only a stalled one."""
    from p1_tpu.node.supervision import RequestSupervisor, SyncStalled

    async def _run():
        genesis = make_genesis(difficulty, retarget)
        headers = [genesis.header]
        hashes = [genesis.block_hash()]
        pos = {hashes[0]: 0}
        from p1_tpu.chain.chain import locator_hashes

        sup = RequestSupervisor(
            stall_timeout_s=stall_timeout_s, attempts_max=attempts_max
        )
        targets = [(host, port), *(tuple(p) for p in fallback_peers)]
        ti = 0
        while True:
            t_host, t_port = targets[ti]
            try:
                async with _session(
                    t_host,
                    t_port,
                    difficulty,
                    retarget,
                    # The handshake is a round too: a half-open target
                    # costs one stall, then the fetch rotates on.
                    handshake_timeout=stall_timeout_s,
                ) as (
                    reader,
                    writer,
                    _,
                ):
                    while True:
                        await protocol.write_frame(
                            writer,
                            protocol.encode_getheaders(
                                locator_hashes(hashes)
                            ),
                        )
                        sup.begin(targets[ti])

                        async def _reply():
                            while True:
                                mtype, body = await _read_msg(reader, writer)
                                if mtype is MsgType.HEADERS:
                                    return body

                        body = await asyncio.wait_for(
                            _reply(), stall_timeout_s
                        )
                        new = [h for h in body if h.block_hash() not in pos]
                        if not new:
                            return headers
                        sup.progress()
                        # A live peer can reorg between batches (and a
                        # failover peer may follow a different branch):
                        # the next reply then restarts below our tip.
                        # Each batch must link to a header we hold —
                        # truncate back to that link point (the stale
                        # branch tail is no longer the serving peer's
                        # main chain) and extend contiguously; anything
                        # that links nowhere is a protocol violation,
                        # not something to append and let verification
                        # blame on an honest peer later.
                        at = pos.get(new[0].prev_hash)
                        if at is None:
                            raise ValueError(
                                "HEADERS reply does not link to the "
                                "known chain"
                            )
                        if at != len(headers) - 1:
                            for h in hashes[at + 1 :]:
                                del pos[h]
                            del headers[at + 1 :]
                            del hashes[at + 1 :]
                        for h in new:
                            if h.prev_hash != hashes[-1]:
                                raise ValueError(
                                    "HEADERS batch is not contiguous"
                                )
                            headers.append(h)
                            hashes.append(h.block_hash())
                            pos[hashes[-1]] = len(hashes) - 1
                        if len(headers) > max_headers:
                            raise ValueError(
                                f"peer served more than {max_headers} "
                                "headers"
                            )
            except (
                ConnectionError,
                OSError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,  # pre-3.11 spelling of the builtin
                TimeoutError,
            ) as e:
                # Stalled round or dead session — never a protocol
                # violation (those raise above).  Rotate to the next
                # target and resume from the headers already held.
                if sup.exhausted():
                    raise SyncStalled(
                        f"headers sync exhausted {attempts_max} failover "
                        f"attempts; last peer {t_host}:{t_port} ({e!r})"
                    ) from e
                delay = sup.record_stall()
                ti = (ti + 1) % len(targets)
                await asyncio.sleep(delay)

    return await asyncio.wait_for(_run(), timeout)


async def get_fees(
    host: str,
    port: int,
    difficulty: int,
    window: int = 0,
    timeout: float = 10.0,
    retarget=None,
) -> protocol.FeeStats:
    """Query confirmed-fee percentiles from the node at host:port — the
    wallet's price signal for `p1 tx --fee auto` (0 window = the node's
    default sample)."""

    async def _run() -> protocol.FeeStats:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getfees(window))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.FEES:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_status(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> dict:
    """Fetch a running node's full status JSON (`p1 status`) — height,
    peers, sync/storage health, and the overload block (governor state,
    admission drops, memory gauge).  Served even while the node sheds
    load, so the probe works exactly when an operator needs it most."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getstatus())
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.STATUS:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def maintain(
    host: str,
    port: int,
    command: dict,
    difficulty: int,
    timeout: float = 30.0,
    retarget=None,
) -> dict:
    """Drive a running node's maintenance plane (`p1 maintain`, v13):
    ``{"op": "status"|"rebase"|"prune"|"compact", ...}`` over
    GETMAINTAIN, returning the MAINTAIN reply — ``{"ok": bool, ...}``.
    A refused command comes back as ``{"ok": false, "error": ...}``:
    the zero-downtime contract means refusals are answers, never
    dropped sessions.  Kept reachable under SHED like GETSTATUS — an
    overloaded node must still accept the operation that relieves it.
    The default timeout is longer than the query probes': a re-base or
    compaction spills real bytes before answering."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(
                writer, protocol.encode_getmaintain(command)
            )
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.MAINTAIN:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_metrics(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> dict:
    """Fetch a node's (or a `p1 serve` replica's) telemetry registry
    snapshot (`p1 metrics`, v12): counters, gauges, and the per-stage
    latency histograms of node/telemetry.py.  Unlike GETSTATUS this
    probe is shed under overload — a refused scrape times out here and
    the caller retries later."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getmetrics())
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.METRICS:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_account(
    host: str,
    port: int,
    account: str,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> protocol.AccountState:
    """Query ``account``'s consensus state (balance, nonce, next usable
    seq) from the node at host:port — what a wallet needs before signing.
    Skips unrelated frames the node pushes at handshake (e.g. its
    GETMEMPOOL request) until the ACCOUNT reply arrives."""

    async def _run() -> protocol.AccountState:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getaccount(account))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.ACCOUNT:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_filters(
    host: str,
    port: int,
    start_height: int,
    count: int,
    difficulty: int,
    timeout: float = 30.0,
    retarget=None,
) -> list[tuple[bytes, bytes]]:
    """Fetch compact block filters for a main-chain height range: (block
    hash, filter bytes) pairs ascending from ``start_height``.  The
    server caps the range — fewer entries than asked means ask again
    from where the reply ended (or the chain ended there)."""

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(
                writer, protocol.encode_getfilters(start_height, count)
            )
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.FILTERS:
                    start, entries = body
                    if start != start_height:
                        raise ValueError(
                            "FILTERS reply for a different start height"
                        )
                    return entries

    return await asyncio.wait_for(_run(), timeout)


class CommitmentViolation(ValueError):
    """A peer's served filter stream contradicts the filter-header
    commitment chain — the one client error that means "this peer is
    lying", not "this peer is slow".  Callers demote the peer (never
    retry it) and fail over; `p1 watch` maps it to exit code 4, the
    same verdict `p1 headers` gives a fake header chain."""


async def _fheaders_range(reader, writer, start: int, count: int, page: int = 1000):
    """Fetch ``count`` filter headers ascending from height ``start``
    over an open session.  Stops early (returns fewer) when the peer's
    committed span ends — FILTERHEADERS is all-or-nothing per request,
    so a short reply is an honest refusal, not a partial answer."""
    out: list[bytes] = []
    h = start
    while len(out) < count:
        await protocol.write_frame(
            writer,
            protocol.encode_getfilterheaders(h, min(page, count - len(out))),
        )
        while True:
            mtype, body = await _read_msg(reader, writer)
            if mtype is MsgType.FILTERHEADERS:
                got_start, headers = body
                break
        if not headers:
            return out
        if got_start != h:
            raise ValueError("FILTERHEADERS reply for a different start height")
        out.extend(headers)
        h += len(headers)
    return out


async def get_filter_headers(
    host: str,
    port: int,
    start_height: int,
    count: int,
    difficulty: int,
    timeout: float = 30.0,
    retarget=None,
    transport=None,
) -> list[bytes]:
    """Fetch the filter-header commitment chain for a height range:
    32-byte headers ascending from ``start_height``, where
    ``header[i] = H(filter_hash[i] || header[i-1])`` anchored at the
    all-zero genesis filter header (chain/filters.py).  Every honest
    replica derives the identical chain from block bytes alone, so two
    peers disagreeing on any height is PROOF at least one is lying —
    the cross-check `filter_scan` and `watch` build their failover on.
    A shorter-than-asked reply means the peer's committed span ends
    there (pruned or still syncing) — honest refusal, not an error."""

    async def _run():
        async with _session(
            host, port, difficulty, retarget, transport=transport
        ) as (reader, writer, _):
            return await _fheaders_range(reader, writer, start_height, count)

    return await asyncio.wait_for(_run(), timeout)


async def _pinned_filter_hash(
    host, port, difficulty, retarget, transport, prev_hash, want_hash
):
    """The TRUE filter hash at one height: fetch the block pinned by
    ``want_hash`` (requested by locator ``prev_hash``), verify the pin
    and its merkle commitment, and compute the filter locally.  Block
    bytes that hash to the pinned header ARE the truth — whichever
    peer serves them cannot influence the result."""
    from p1_tpu.chain.filters import block_filter, filter_hash

    async with _session(
        host, port, difficulty, retarget, transport=transport
    ) as (reader, writer, _):
        await protocol.write_frame(writer, protocol.encode_getblocks([prev_hash]))
        while True:
            mtype, body = await _read_msg(reader, writer)
            if mtype is MsgType.BLOCKS:
                break
        if not body or body[0].block_hash() != want_hash:
            raise ValueError("peer did not serve the hash-pinned block")
        if not body[0].merkle_ok():
            raise ValueError("pinned block fails its merkle commitment")
        return filter_hash(block_filter(body[0]))


async def _adjudicate(
    mine: list[bytes],
    other,
    hashes: list[bytes],
    upto: int,
    difficulty: int,
    retarget,
    transport,
) -> str:
    """Two peers disagree on the filter-header chain at height ``upto``
    — name the liar.  ``mine`` is the serving peer's full committed
    chain [0..upto]; ``other`` is (host, port) of the disagreeing peer;
    ``hashes`` is the hash-pinned header skeleton.  Finds the first
    diverging height d (everything below is agreed, and the genesis
    anchor is agreed by construction), fetches the hash-pinned block at
    d, computes the true filter hash locally, and checks which side's
    header[d] extends the agreed prefix with the truth.  Returns
    "self" (serving peer lies), "other" (cross-check peer lies), or
    "both" (neither side committed the true filter)."""
    from p1_tpu.chain.filters import (
        GENESIS_FILTER_HEADER,
        block_filter,
        filter_hash,
        next_filter_header,
    )

    theirs = await get_filter_headers(
        *other, 0, upto + 1, difficulty, retarget=retarget, transport=transport
    )
    if len(mine) != upto + 1 or len(theirs) != upto + 1:
        raise ValueError("commitment span vanished during adjudication")
    d = next(i for i in range(upto + 1) if mine[i] != theirs[i])
    prev = GENESIS_FILTER_HEADER if d == 0 else mine[d - 1]
    if d == 0:
        # Genesis is local knowledge — no fetch needed.
        fhash_true = filter_hash(block_filter(make_genesis(difficulty, retarget)))
    else:
        try:
            fhash_true = await _pinned_filter_hash(
                *other, difficulty, retarget, transport, hashes[d - 1], hashes[d]
            )
        except (ConnectionError, OSError, ValueError, asyncio.IncompleteReadError):
            # The cross-check peer won't serve the block; without it the
            # dispute cannot be settled from this side alone.
            raise ValueError(
                "adjudication peer refused the hash-pinned block"
            ) from None
    truth = next_filter_header(fhash_true, prev)
    if truth == mine[d]:
        return "other"
    if truth == theirs[d]:
        return "self"
    return "both"


async def get_snapshot(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 60.0,
    retarget=None,
    out_path=None,
):
    """Fetch the node's current state snapshot (chain/snapshot.py):
    manifest first, then chunk ranges, each chunk verified against its
    manifest digest AS IT ARRIVES and the state root checked at the end
    — the same incremental integrity contract the node's own snapshot
    boot applies.  Returns a fully verified ``LedgerSnapshot`` (or None
    when the peer serves no snapshot); ``out_path`` additionally writes
    the CRC-framed snapshot file.  The STATE is still only the serving
    peer's claim — only replaying the history proves it (the trust
    model `p1 snapshot` prints)."""
    from p1_tpu.chain import snapshot as chain_snapshot

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):

            async def _reply():
                while True:
                    mtype, body = await _read_msg(reader, writer)
                    if mtype is MsgType.SNAPSHOT:
                        return body

            await protocol.write_frame(writer, protocol.encode_getsnapshot(0, 0))
            body = await _reply()
            if body[0] == "none":
                return None
            if body[0] != "manifest":
                raise ValueError("peer answered chunks before the manifest")
            manifest_payload = body[1]
            manifest = chain_snapshot.parse_manifest(manifest_payload)
            chunks: list[bytes] = []
            while len(chunks) < len(manifest.chunk_digests):
                await protocol.write_frame(
                    writer, protocol.encode_getsnapshot(len(chunks), 8)
                )
                body = await _reply()
                if body[0] != "chunks" or body[1] != len(chunks) or not body[2]:
                    raise ValueError("bad SNAPSHOT chunk range from peer")
                for payload in body[2]:
                    i = len(chunks)
                    if (
                        i >= len(manifest.chunk_digests)
                        or chain_snapshot.chunk_digest(payload)
                        != manifest.chunk_digests[i]
                    ):
                        raise ValueError(f"chunk {i} fails its manifest digest")
                    chunks.append(payload)
            snap = chain_snapshot.assemble(manifest, chunks)
            if out_path is not None:
                chain_snapshot.write_snapshot(out_path, manifest_payload, chunks)
            return snap

    return await asyncio.wait_for(_run(), timeout)


async def filter_scan(
    host: str,
    port: int,
    watch_items,
    difficulty: int,
    timeout: float = 120.0,
    retarget=None,
    fetch_blocks: bool = True,
    start_height: int = 1,
    page: int = 500,
    fallback_peers=(),
    verify_commitment: bool = True,
    transport=None,
):
    """Light-client sync by filter match (the round-9 serving plane's
    wallet flow): ONE session that

    1. syncs the peer's header chain (GETHEADERS locator rounds — the
       ~80 B/block skeleton),
    2. pages the compact filter stream (GETFILTERS) and matches
       ``watch_items`` (account ids as utf-8 bytes, and/or txids)
       locally — the peer never learns WHICH accounts the wallet
       watches, and the wallet asks zero per-address questions,
    3. fetches only the matching blocks (rare: the designed false-
       positive rate per absent item is ~1/M ≈ 1.3e-6) and pins each to
       the header chain by hash, dropping any filter false positives
       after inspection.

    Returns ``(headers, matches)`` where matches is a list of
    ``(height, block)`` — or ``(headers, [(height, block_hash), ...])``
    with ``fetch_blocks=False`` for callers that only want locations.
    Zero false negatives is the filter construction's guarantee
    (chain/filters.py): every block that actually touches a watched
    item IS in the matches (property-tested against full block scans).

    Trust model: same as ``get_headers`` — the header chain should be
    verified by the caller (``replay_host``); filters and blocks are
    pinned to it by hash, and fetched blocks are checked against their
    header's merkle commitment here, so a lying peer can omit service
    but cannot substitute content.

    Commitment verification (``verify_commitment``, v14): every served
    filter is checked against the peer's own filter-header chain
    (``header[i] = H(filter_hash[i] || header[i-1])``, genesis-anchored
    so the whole prefix is verified from local knowledge when the scan
    starts at height 1).  A peer whose filters contradict its own
    commitments raises ``CommitmentViolation`` immediately.  With
    ``fallback_peers``, the committed tip is also cross-checked against
    an independent replica; a disagreement is adjudicated by fetching
    the hash-pinned block at the first diverging height and computing
    the true filter locally — the proven liar is DEMOTED (never asked
    again this call) and the scan fails over to the next peer, so a
    wallet behind one dishonest replica still gets every confirmation.
    """
    from p1_tpu.chain.chain import locator_hashes
    from p1_tpu.chain.filters import (
        GENESIS_FILTER_HEADER,
        block_filter,
        filter_hash,
        matches_any,
        next_filter_header,
    )

    items = [
        it.encode("utf-8") if isinstance(it, str) else bytes(it)
        for it in watch_items
    ]
    demoted: set = set()

    async def _scan_one(t_host, t_port, cross_peers):
        genesis = make_genesis(difficulty, retarget)
        headers = [genesis.header]
        hashes = [genesis.block_hash()]
        pos = {hashes[0]: 0}
        async with _session(
            t_host, t_port, difficulty, retarget, transport=transport
        ) as (
            reader,
            writer,
            _,
        ):

            async def _reply(want):
                while True:
                    mtype, body = await _read_msg(reader, writer)
                    if mtype is want:
                        return body

            # 1. headers skeleton (single-session variant of get_headers;
            # the supervised multi-peer fetch lives there — this scan is
            # one wallet round against one chosen peer).
            while True:
                await protocol.write_frame(
                    writer, protocol.encode_getheaders(locator_hashes(hashes))
                )
                batch = await _reply(MsgType.HEADERS)
                new = [h for h in batch if h.block_hash() not in pos]
                if not new:
                    break
                at = pos.get(new[0].prev_hash)
                if at is None:
                    raise ValueError(
                        "HEADERS reply does not link to the known chain"
                    )
                if at != len(headers) - 1:
                    for h in hashes[at + 1 :]:
                        del pos[h]
                    del headers[at + 1 :]
                    del hashes[at + 1 :]
                for h in new:
                    if h.prev_hash != hashes[-1]:
                        raise ValueError("HEADERS batch is not contiguous")
                    headers.append(h)
                    hashes.append(h.block_hash())
                    pos[hashes[-1]] = len(hashes) - 1

            # 2. filter stream + local match, recording each accepted
            # filter's hash so step 2b can replay the commitment chain.
            matched: list[tuple[int, bytes]] = []
            scan_lo = max(1, start_height)
            fhashes: dict[int, bytes] = {}
            verified_to = scan_lo - 1
            h = scan_lo
            while h < len(hashes):
                await protocol.write_frame(
                    writer,
                    protocol.encode_getfilters(
                        h, min(page, len(hashes) - h)
                    ),
                )
                start, entries = await _reply(MsgType.FILTERS)
                if not entries:
                    break
                stop = False
                for i, (bhash, fbytes) in enumerate(entries):
                    height = start + i
                    if height >= len(hashes):
                        stop = True  # peer's chain ran ahead of our skeleton
                        break
                    if bhash != hashes[height]:
                        # The peer reorged between the header sync and
                        # this page; the stale tail's filters are for
                        # blocks we did not pin — stop at the divergence
                        # (a fuller client would re-sync headers).
                        stop = True
                        break
                    if items and matches_any(fbytes, bhash, items):
                        matched.append((height, bhash))
                    fhashes[height] = filter_hash(fbytes)
                    verified_to = height
                if stop:
                    break
                h = start + len(entries)

            # 2b. replay the peer's filter-header commitment chain over
            # the filters it just served.  Starting at height 1 the
            # anchor is the all-zero genesis header — fully verified
            # from local knowledge; a deeper start trusts the anchor
            # unless a fallback corroborates the tip below.
            if verify_commitment and verified_to >= scan_lo:
                served = await _fheaders_range(
                    reader, writer, scan_lo - 1, verified_to - scan_lo + 2
                )
                if len(served) == verified_to - scan_lo + 2:
                    prev = served[0]
                    if scan_lo == 1:
                        want_anchor = next_filter_header(
                            filter_hash(block_filter(genesis)),
                            GENESIS_FILTER_HEADER,
                        )
                        if prev != want_anchor:
                            raise CommitmentViolation(
                                f"{t_host}:{t_port} commits a wrong "
                                "genesis filter header"
                            )
                    for off, height in enumerate(
                        range(scan_lo, verified_to + 1)
                    ):
                        expect = next_filter_header(fhashes[height], prev)
                        if served[off + 1] != expect:
                            raise CommitmentViolation(
                                f"{t_host}:{t_port} served a filter at "
                                f"height {height} that contradicts its "
                                "own commitment chain"
                            )
                        prev = expect
                    # Cross-check the committed tip against independent
                    # replicas: honest peers derive the identical chain,
                    # so any disagreement has exactly one explanation —
                    # somebody forged a filter — and the hash-pinned
                    # block at the divergence names them.
                    for peer in list(cross_peers):
                        if peer in demoted:
                            continue
                        try:
                            theirs = await get_filter_headers(
                                *peer,
                                verified_to,
                                1,
                                difficulty,
                                retarget=retarget,
                                transport=transport,
                            )
                        except (
                            ConnectionError,
                            OSError,
                            ValueError,
                            asyncio.IncompleteReadError,
                            asyncio.TimeoutError,
                            TimeoutError,
                        ):
                            continue  # unreachable/short peer ≠ evidence
                        if not theirs or theirs[0] == prev:
                            continue
                        mine = await _fheaders_range(
                            reader, writer, 0, verified_to + 1
                        )
                        verdict = await _adjudicate(
                            mine,
                            peer,
                            hashes,
                            verified_to,
                            difficulty,
                            retarget,
                            transport,
                        )
                        if verdict in ("other", "both"):
                            demoted.add(peer)
                        if verdict in ("self", "both"):
                            raise CommitmentViolation(
                                f"{t_host}:{t_port} serves forged filters "
                                f"(proven at cross-check vs "
                                f"{peer[0]}:{peer[1]})"
                            )

            if not fetch_blocks:
                return headers, matched

            # 3. fetch ONLY the matched blocks, pinned by hash; drop
            # false positives after inspection (a block whose filter
            # matched but that touches none of the watched items).
            out = []
            for height, bhash in matched:
                await protocol.write_frame(
                    writer,
                    protocol.encode_getblocks([hashes[height - 1]]),
                )
                blocks = await _reply(MsgType.BLOCKS)
                if not blocks or blocks[0].block_hash() != bhash:
                    raise ValueError(
                        "peer did not serve the filter-matched block"
                    )
                block = blocks[0]
                if not block.merkle_ok():
                    raise ValueError(
                        "matched block fails its merkle commitment"
                    )
                touched = set()
                for tx in block.txs:
                    touched.add(tx.txid())
                    touched.add(tx.sender.encode("utf-8"))
                    touched.add(tx.recipient.encode("utf-8"))
                if any(it in touched for it in items):
                    out.append((height, block))
            return headers, out

    async def _run():
        targets = [(host, port), *(tuple(p) for p in fallback_peers)]
        last_exc: CommitmentViolation | None = None
        for i, (t_host, t_port) in enumerate(targets):
            if (t_host, t_port) in demoted:
                continue
            others = [t for j, t in enumerate(targets) if j != i]
            try:
                return await _scan_one(t_host, t_port, others)
            except CommitmentViolation as e:
                # A proven liar: demote (never re-ask) and fail over to
                # the next replica with the same watch list.
                demoted.add((t_host, t_port))
                last_exc = e
        raise last_exc if last_exc is not None else CommitmentViolation(
            "all peers demoted"
        )

    return await asyncio.wait_for(_run(), timeout)


class ReplicaSet:
    """Wallet-side target selection over a replica fleet — the policy
    that replaces ``watch``'s static fallback tuple (ROADMAP item 2's
    fleet half).

    The set holds an ordered list of replica addresses plus an optional
    ``full_node`` of last resort, and scores every target from the
    signals the watch loop already produces: dead/stalled sessions
    (``note_stall``), EVENTGAP shedding (``note_gap``), verified events
    (``note_event``), cross-check corroborations (``note_agreement``),
    and proven commitment violations (``note_violation`` — permanent
    demotion, same contract as ``watch``'s demoted set).  ``pick()``
    returns the healthiest live replica, preferring targets whose
    filter-header chains have agreed at cross-checks, and sheds to the
    full node ONLY when every replica is demoted or mid-outage
    (``SHED_AFTER`` consecutive dead sessions) — read capacity stays on
    the replica tier unless the tier is actually gone.

    ``spread_key`` rotates tie-breaks so a fleet of wallets started
    with distinct keys (e.g. a session serial) spreads its
    subscriptions across replicas instead of dog-piling the first
    address.  ``update_targets`` rebalances live: a replica that died
    leaves (its health forgotten), a freshly provisioned one joins cold
    and, being unscored, is immediately eligible — the elastic-fleet
    seam the chaos ``replica_join`` op drives.

    Everything here is deterministic (no clock, no randomness): the
    same signal sequence always selects the same targets, which is what
    lets the chaos plane put ReplicaSet-driven wallets inside the
    trace-digest contract."""

    #: Consecutive dead/stalled sessions after which a replica counts
    #: as mid-outage for the shed-to-full-node decision.
    SHED_AFTER = 2

    def __init__(self, replicas, *, full_node=None, spread_key: int = 0):
        self.full_node = tuple(full_node) if full_node is not None else None
        self.spread_key = int(spread_key)
        self.demoted: set[tuple] = set()
        self.failovers = 0
        self.rebalances = 0
        self.active: tuple | None = None
        self._order: list[tuple] = []
        self._health: dict[tuple, dict] = {}
        self.update_targets(replicas)
        self.rebalances = 0  # construction is not a rebalance

    def __len__(self) -> int:
        return len(self._order)

    @staticmethod
    def _fresh() -> dict:
        return {
            "stalls": 0,  # dead/stalled sessions, cumulative
            "gaps": 0,  # EVENTGAP shed notices
            "events": 0,  # verified events served
            "agreements": 0,  # cross-check corroborations
            "streak": 0,  # CONSECUTIVE stalls since the last event
        }

    def _h(self, target) -> dict:
        t = tuple(target)
        h = self._health.get(t)
        if h is None:
            h = self._health[t] = self._fresh()
        return h

    # -- policy signals ------------------------------------------------

    def note_stall(self, target) -> None:
        h = self._h(target)
        h["stalls"] += 1
        h["streak"] += 1

    def note_gap(self, target) -> None:
        self._h(target)["gaps"] += 1

    def note_event(self, target) -> None:
        h = self._h(target)
        h["events"] += 1
        h["streak"] = 0

    def note_agreement(self, target) -> None:
        self._h(target)["agreements"] += 1

    def note_violation(self, target) -> None:
        """Proven commitment violation: permanent demotion."""
        self.demoted.add(tuple(target))

    # -- membership ----------------------------------------------------

    def update_targets(self, replicas) -> tuple[list, list]:
        """Rebalance to a new replica list (a replica died, a fresh one
        joined): returns ``(joined, left)``.  Health carries over for
        replicas that persist; leavers are forgotten entirely (a
        re-provisioned address starts cold), and demotions are NOT
        forgotten — a liar that rejoins under the same address stays
        demoted."""
        new = list(dict.fromkeys(tuple(p) for p in replicas))
        seen = set(new)
        left = [t for t in self._order if t not in seen]
        joined = [t for t in new if t not in self._health]
        self._order = new
        for t in joined:
            self._health[t] = self._fresh()
        for t in left:
            self._health.pop(t, None)
        if joined or left:
            self.rebalances += 1
        if (
            self.active is not None
            and self.active not in seen
            and self.active != self.full_node
        ):
            self.active = None
        return joined, left

    def peers(self) -> list[tuple]:
        """Every target (replicas, then the full node) — the universe
        the watch cross-check corroborates against."""
        out = list(self._order)
        if self.full_node is not None and self.full_node not in out:
            out.append(self.full_node)
        return out

    def live(self) -> list[tuple]:
        return [t for t in self.peers() if t not in self.demoted]

    # -- selection -----------------------------------------------------

    def _score(self, target) -> float:
        """Lower is better.  The consecutive-stall streak dominates
        (a replica mid-outage must lose to any healthy one fast);
        cumulative stalls and shed gaps drag; agreement at cross-checks
        and served events earn bounded preference (bounded so a
        long-lived favorite cannot become unsheddable)."""
        h = self._h(target)
        return (
            4.0 * h["streak"]
            + 1.0 * h["stalls"]
            + 0.5 * h["gaps"]
            - 2.0 * min(h["agreements"], 8)
            - 0.05 * min(h["events"], 32)
        )

    def pick(self) -> tuple | None:
        """The target the next session should dial: the healthiest live
        replica (ties broken by ``spread_key``-rotated join order), the
        full node when the replica tier is exhausted, None when every
        target is demoted (the caller raises)."""
        replicas = [t for t in self._order if t not in self.demoted]
        node_ok = (
            self.full_node is not None and self.full_node not in self.demoted
        )
        if not replicas:
            return self.full_node if node_ok else None
        if node_ok and all(
            self._h(t)["streak"] >= self.SHED_AFTER for t in replicas
        ):
            return self.full_node
        n = len(self._order)
        return min(
            replicas,
            key=lambda t: (
                self._score(t),
                (self._order.index(t) - self.spread_key) % n,
            ),
        )

    def mark_active(self, target) -> None:
        """Record the target a session is now riding; counts a failover
        whenever it differs from the previous one."""
        t = tuple(target)
        if self.active is not None and self.active != t:
            self.failovers += 1
        self.active = t

    def snapshot(self) -> dict:
        """The replica-health/selection surface (`p1 watch` JSON,
        OBSERVABILITY.md catalog)."""
        return {
            "replicas": len(self._order),
            "demoted": len(self.demoted),
            "failovers": self.failovers,
            "rebalances": self.rebalances,
            "active": (
                f"{self.active[0]}:{self.active[1]}" if self.active else None
            ),
            "health": {
                f"{h}:{p}": dict(v) for (h, p), v in self._health.items()
            },
        }


async def watch(
    host: str,
    port: int,
    watch_items,
    difficulty: int,
    *,
    retarget=None,
    cursor: tuple[int, bytes] | None = None,
    fallback_peers=(),
    replica_set: ReplicaSet | None = None,
    transport=None,
    handshake_timeout: float = 10.0,
    cross_check_every: int = 32,
    rewind_ring: int = 1024,
    reconnect_delay_s: float = 0.25,
    max_session_failures: int | None = None,
):
    """Live wallet push plane (v14): SUBSCRIBE to a node or replica and
    yield one verified dict per connected block —

        {"height", "block_hash", "filter_header", "matched",
         "txids", "peer"}

    ``matched`` is re-derived LOCALLY from the pushed filter (the
    server's claim is only a hint, as is ``txids`` — a wallet confirms
    a payment by fetching the block or an SPV proof, both hash-pinned).

    Verify-before-believe, per event: the raw header must link to the
    previous verified block and carry the chain's proof of work, and the
    pushed filter must extend the filter-header commitment chain from
    the last verified cursor (``H(filter_hash || prev)``).  Any
    contradiction raises/handles ``CommitmentViolation``: the peer is
    DEMOTED and the watch fails over to the next of ``fallback_peers``,
    re-subscribing at the last verified cursor so the new replica
    replays exactly the missed window — zero missed confirmations
    across a lying or dying replica.

    Degradation handling: a coalesce hole (skipped heights) or an
    explicit gap notice triggers a cursor re-subscribe on the same
    session — the server replays the hole as full events.  A server
    that keeps shedding rotates like a dead one.  Reorgs rewind through
    a ring of the last ``rewind_ring`` verified blocks; deeper reorgs
    reset the anchor (the wallet should rescan history — see below).

    Trust scope: with a ``cursor`` (the last (height, filter_header)
    the CALLER verified, e.g. from a prior ``filter_scan``), everything
    yielded is anchored to that knowledge.  Without one, the anchor is
    trust-on-first-use at the serving peer's committed tip and the
    watch verifies FORWARD from there — historical verification is
    ``filter_scan``'s job.  ``cross_check_every`` events, the committed
    tip is compared against an independent fallback; disagreement is
    adjudicated via the hash-pinned block at the first divergence when
    the ring still covers it, else resolved conservatively by failing
    over.  ``max_session_failures`` bounds consecutive dead sessions
    (None = retry forever; daemons bound the watch by deadline/cancel
    instead).

    Target selection: a ``replica_set`` (``ReplicaSet``) makes the
    fleet policy explicit — health-scored selection, agreement
    preference, shed-to-full-node, live rebalancing via
    ``update_targets`` — and ``host``/``port`` are then ignored for
    dialing (the set picks).  Without one, an internal set over
    ``[(host, port), *fallback_peers]`` reproduces the classic
    rotate-on-failure order (all targets start tied, so join order
    breaks ties exactly like the old round-robin)."""
    from p1_tpu.chain.filters import (
        filter_hash,
        matches_any,
        next_filter_header,
    )
    from p1_tpu.core.header import BlockHeader, meets_target

    items = [
        it.encode("utf-8") if isinstance(it, str) else bytes(it)
        for it in watch_items
    ]
    if not items:
        raise ValueError("watch needs at least one watch item")

    if replica_set is not None and fallback_peers:
        raise ValueError("pass either replica_set or fallback_peers")
    rs = (
        replica_set
        if replica_set is not None
        else ReplicaSet([(host, port), *(tuple(p) for p in fallback_peers)])
    )
    anchor = (int(cursor[0]), bytes(cursor[1])) if cursor is not None else None
    anchor_bhash: bytes | None = None
    ring: dict[int, tuple[bytes, bytes]] = {}  # height -> (bhash, fheader)
    failures = 0
    events_seen = 0
    last_violation: CommitmentViolation | None = None
    net_errors = (
        ConnectionError,
        OSError,
        asyncio.IncompleteReadError,
        asyncio.TimeoutError,
        TimeoutError,
    )

    async def _cross_check(serving, height, fheader):
        """Compare our verified committed tip against one independent
        replica; on disagreement, adjudicate and demote the proven
        liar.  Raises CommitmentViolation when the SERVING peer loses
        (or when the divergence predates what this watch verified —
        conservative: fail over rather than keep riding a suspect)."""
        for peer in rs.peers():
            if peer == serving or peer in rs.demoted:
                continue
            try:
                theirs = await get_filter_headers(
                    *peer, height, 1, difficulty,
                    retarget=retarget, transport=transport,
                )
            except net_errors + (ValueError,):
                continue  # unreachable/short peer is not evidence
            if not theirs:
                continue
            if theirs[0] == fheader:
                # Corroborated: both chains agree — the agreement
                # preference the selection policy feeds on.
                rs.note_agreement(serving)
                rs.note_agreement(peer)
                return
            try:
                mine_chain = await get_filter_headers(
                    *serving, 0, height + 1, difficulty,
                    retarget=retarget, transport=transport,
                )
            except net_errors + (ValueError,):
                return
            if len(mine_chain) != height + 1:
                return
            cover = {hh: ring[hh][0] for hh in ring}
            try:
                verdict = await _adjudicate(
                    mine_chain, peer, cover, height,
                    difficulty, retarget, transport,
                )
            except KeyError:
                # First divergence below the ring: cannot fetch the
                # pinned block to prove who lies — prefer failover.
                verdict = "self"
            except net_errors + (ValueError,):
                continue
            if verdict in ("other", "both"):
                rs.note_violation(peer)
            if verdict in ("self", "both"):
                raise CommitmentViolation(
                    f"{serving[0]}:{serving[1]} filter-header chain "
                    f"disproven against {peer[0]}:{peer[1]}"
                )
            else:
                rs.note_agreement(serving)
            return

    while True:
        serving = rs.pick()
        if serving is None:
            if last_violation is not None:
                raise last_violation
            raise ConnectionError("all watch peers demoted")
        rs.mark_active(serving)
        got_event = False
        try:
            async with _session(
                *serving,
                difficulty,
                retarget,
                handshake_timeout=handshake_timeout,
                transport=transport,
            ) as (reader, writer, hello):
                if anchor is None:
                    # TOFU anchor at the peer's committed tip — walk
                    # back from its claimed height to the end of the
                    # committed span (replica refresh lag is ~0..1).
                    h = hello.tip_height
                    while h >= 0 and anchor is None:
                        got = await _fheaders_range(reader, writer, h, 1)
                        if got:
                            anchor = (h, got[0])
                        else:
                            h -= 1
                    if anchor is None:
                        raise ConnectionError(
                            "peer commits no filter headers yet"
                        )
                await protocol.write_frame(
                    writer, protocol.encode_subscribe(items, anchor)
                )
                bridge_rounds = 0
                while True:
                    mtype, ev = await _read_msg(reader, writer)
                    if mtype is not MsgType.EVENT:
                        continue
                    if isinstance(ev, protocol.GapEvent):
                        # Drop-to-cursor notice: re-subscribe at our
                        # verified anchor; the server replays the hole
                        # as full events (no separate bridge protocol).
                        # (A draining replica sends one of these as its
                        # goodbye, then refuses the re-subscribe — the
                        # net error below fails over cursor-intact.)
                        rs.note_gap(serving)
                        bridge_rounds += 1
                        if bridge_rounds > 8:
                            raise ConnectionError(
                                "peer keeps shedding this session"
                            )
                        await protocol.write_frame(
                            writer, protocol.encode_subscribe(items, anchor)
                        )
                        continue
                    header = BlockHeader.deserialize(ev.raw_header)
                    bhash = header.block_hash()
                    hv = ev.height
                    if hv <= anchor[0]:
                        # Reorg: the server walked back.  Rewind to the
                        # fork point through the verified ring.
                        ent = ring.get(hv - 1)
                        if ent is None:
                            anchor = None
                            anchor_bhash = None
                            ring.clear()
                            raise ConnectionError(
                                "reorg deeper than the rewind ring"
                            )
                        for k in [k for k in ring if k >= hv]:
                            del ring[k]
                        anchor = (hv - 1, ent[1])
                        anchor_bhash = ent[0]
                    if hv != anchor[0] + 1:
                        # Coalesce hole: replay it via cursor
                        # re-subscribe (replaces this session's sub).
                        bridge_rounds += 1
                        if bridge_rounds > 8:
                            raise ConnectionError(
                                "peer cannot replay the hole"
                            )
                        await protocol.write_frame(
                            writer, protocol.encode_subscribe(items, anchor)
                        )
                        continue
                    # Verify before believing.
                    if (
                        anchor_bhash is not None
                        and header.prev_hash != anchor_bhash
                    ):
                        raise CommitmentViolation(
                            f"{serving[0]}:{serving[1]} pushed a header "
                            "that does not link to the verified chain"
                        )
                    if not meets_target(bhash, header.difficulty) or (
                        retarget is None and header.difficulty != difficulty
                    ):
                        raise CommitmentViolation(
                            f"{serving[0]}:{serving[1]} pushed a header "
                            "without the chain's proof of work"
                        )
                    expect_fh = next_filter_header(
                        filter_hash(ev.filter), anchor[1]
                    )
                    if expect_fh != ev.filter_header:
                        raise CommitmentViolation(
                            f"{serving[0]}:{serving[1]} pushed a filter "
                            "that contradicts the commitment chain at "
                            f"height {hv}"
                        )
                    local_matched = matches_any(ev.filter, bhash, items)
                    ring[hv] = (bhash, expect_fh)
                    if len(ring) > rewind_ring:
                        del ring[min(ring)]
                    anchor = (hv, expect_fh)
                    anchor_bhash = bhash
                    bridge_rounds = 0
                    got_event = True
                    failures = 0
                    events_seen += 1
                    rs.note_event(serving)
                    if (
                        cross_check_every
                        and len(rs.live()) > 1
                        and events_seen % cross_check_every == 0
                    ):
                        await _cross_check(serving, hv, expect_fh)
                    yield {
                        "height": hv,
                        "block_hash": bhash,
                        "filter_header": expect_fh,
                        "matched": local_matched,
                        "txids": tuple(ev.txids),
                        "peer": serving,
                        "failovers": rs.failovers,
                    }
        except CommitmentViolation as e:
            # Proven liar: never ask again, fail over at the verified
            # cursor — the next replica replays the missed window.
            rs.note_violation(serving)
            last_violation = e
        except net_errors:
            # Dead/stalled/refusing session — not evidence of lying.
            # A session that dies before ANY event may mean the cursor
            # was refused (our anchor reorged away, or sits past a
            # pruned window): after repeated refusals, rewind the
            # anchor one verified ring step and try again.
            rs.note_stall(serving)
            if not got_event:
                failures += 1
                if (
                    max_session_failures is not None
                    and failures >= max_session_failures
                ):
                    raise
                if failures >= 2 and anchor is not None:
                    lower = [k for k in ring if k < anchor[0]]
                    if lower:
                        k = max(lower)
                        anchor = (k, ring[k][1])
                        anchor_bhash = ring[k][0]
            await asyncio.sleep(reconnect_delay_s)
