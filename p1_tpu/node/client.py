"""Thin p2p client: one-shot wallet/tooling rounds against a running node.

Capability parity: a usable mempool needs an entry point for transactions
from outside the node process (BASELINE.json:5 names the mempool; without
this, only miners' own processes could ever create payload for blocks).
Each client call speaks one round of the ordinary peer protocol — HELLO
exchange (validating genesis, i.e. that both sides run the same chain
parameters), then its one request — and disconnects; the node treats the
client like any short-lived peer.
"""

from __future__ import annotations

import asyncio
import contextlib

from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.tx import Transaction
from p1_tpu.node import protocol
from p1_tpu.node.protocol import Hello, MsgType


@contextlib.asynccontextmanager
async def _session(
    host: str,
    port: int,
    difficulty: int,
    retarget=None,
    handshake_timeout: float | None = None,
):
    """Connect + HELLO-validate against the chain selected by
    ``difficulty`` (+ optional ``RetargetRule`` — part of chain identity);
    yields (reader, writer, peer_hello).  The ONE copy of the handshake
    all clients share — a protocol change lands here once.

    ``handshake_timeout`` bounds connect + HELLO exchange with its own
    deadline: a half-open peer (accepts TCP, never answers — a dead
    process behind a live listen backlog) must cost a supervised caller
    one stall, not its entire overall timeout.  None keeps the caller's
    outer ``wait_for`` as the only bound (the one-shot clients, whose
    whole round is already a single short timeout)."""

    async def _connect():
        reader, writer = await asyncio.open_connection(host, port)
        try:
            genesis_hash = make_genesis(difficulty, retarget).block_hash()
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(genesis_hash, 0, 0))
            )
            mtype, hello = protocol.decode(
                await protocol.read_frame(reader)
            )
            if mtype is not MsgType.HELLO:
                raise ValueError("node did not HELLO")
            if hello.genesis_hash != genesis_hash:
                raise ValueError(
                    "genesis mismatch: node runs a different chain "
                    "(check --difficulty / retarget flags)"
                )
            return reader, writer, hello
        except BaseException:
            # Incl. the cancellation a handshake timeout injects: the
            # socket must not outlive the abandoned attempt.
            writer.close()
            raise

    if handshake_timeout is None:
        reader, writer, hello = await _connect()
    else:
        reader, writer, hello = await asyncio.wait_for(
            _connect(), handshake_timeout
        )
    try:
        yield reader, writer, hello
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


async def _read_msg(reader, writer):
    """One decoded frame, transparently answering keepalive PINGs — a
    client mid-round (e.g. a long headers sync between requests) must
    show liveness or the node's idle probe evicts it (node.py)."""
    while True:
        mtype, body = protocol.decode(await protocol.read_frame(reader))
        if mtype is MsgType.PING:
            await protocol.write_frame(writer, protocol.encode_pong(body))
            continue
        return mtype, body


async def send_tx(
    host: str,
    port: int,
    tx: Transaction,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> int:
    """Push ``tx`` to the node at host:port; return the node's tip height.

    ``difficulty`` selects the chain (it determines the genesis block the
    HELLO handshake validates against); a mismatch raises ValueError
    rather than silently feeding a transaction to the wrong network.
    """

    async def _run() -> int:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            hello,
        ):
            await protocol.write_frame(writer, protocol.encode_tx(tx))
            return hello.tip_height

    return await asyncio.wait_for(_run(), timeout)


async def get_proof(
    host: str,
    port: int,
    txid: bytes,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
):
    """Fetch the SPV inclusion proof for ``txid`` from the node at
    host:port.  Returns a ``TxProof`` or ``None`` (not confirmed on the
    node's main chain).  The caller verifies the proof itself with
    ``p1_tpu.chain.verify_tx_proof`` — never trust, always check."""

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getproof(txid))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.PROOF:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_headers(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 60.0,
    retarget=None,
    max_headers: int = 1_000_000,
    stall_timeout_s: float = 15.0,
    fallback_peers=(),
    attempts_max: int = 4,
):
    """Headers-first light-client sync: the node's full main-chain header
    list, genesis-first, ~80 B per block.  Fetches until a reply adds
    nothing new; the CALLER must then verify the chain itself with
    ``p1_tpu.chain.replay_host`` (PoW, linkage, difficulty schedule) —
    this function moves bytes, it does not bless them.  ``max_headers``
    bounds memory against a responder that streams garbage forever.

    Supervised (node/supervision.py, the same layer the node runs over
    its own locator sync): each GETHEADERS round must land a reply that
    grows the chain within ``stall_timeout_s``, or the session is
    abandoned and the fetch resumes — accumulated headers kept — against
    the next address in ``[primary, *fallback_peers]`` after a jittered
    backoff.  The locator is rebuilt from what we already hold, so a
    failover re-fetches at most one batch, and the link-point truncation
    below already handles a fallback peer on a different (heavier-tip)
    branch.  ``attempts_max`` consecutive stalls raise ``SyncStalled``;
    progress resets the budget, so an honest-slow peer that keeps
    serving batches is never abandoned.  Protocol violations (unlinked
    or non-contiguous batches) still raise ``ValueError`` immediately —
    a lying peer is not retried, only a stalled one."""
    from p1_tpu.node.supervision import RequestSupervisor, SyncStalled

    async def _run():
        genesis = make_genesis(difficulty, retarget)
        headers = [genesis.header]
        hashes = [genesis.block_hash()]
        pos = {hashes[0]: 0}
        from p1_tpu.chain.chain import locator_hashes

        sup = RequestSupervisor(
            stall_timeout_s=stall_timeout_s, attempts_max=attempts_max
        )
        targets = [(host, port), *(tuple(p) for p in fallback_peers)]
        ti = 0
        while True:
            t_host, t_port = targets[ti]
            try:
                async with _session(
                    t_host,
                    t_port,
                    difficulty,
                    retarget,
                    # The handshake is a round too: a half-open target
                    # costs one stall, then the fetch rotates on.
                    handshake_timeout=stall_timeout_s,
                ) as (
                    reader,
                    writer,
                    _,
                ):
                    while True:
                        await protocol.write_frame(
                            writer,
                            protocol.encode_getheaders(
                                locator_hashes(hashes)
                            ),
                        )
                        sup.begin(targets[ti])

                        async def _reply():
                            while True:
                                mtype, body = await _read_msg(reader, writer)
                                if mtype is MsgType.HEADERS:
                                    return body

                        body = await asyncio.wait_for(
                            _reply(), stall_timeout_s
                        )
                        new = [h for h in body if h.block_hash() not in pos]
                        if not new:
                            return headers
                        sup.progress()
                        # A live peer can reorg between batches (and a
                        # failover peer may follow a different branch):
                        # the next reply then restarts below our tip.
                        # Each batch must link to a header we hold —
                        # truncate back to that link point (the stale
                        # branch tail is no longer the serving peer's
                        # main chain) and extend contiguously; anything
                        # that links nowhere is a protocol violation,
                        # not something to append and let verification
                        # blame on an honest peer later.
                        at = pos.get(new[0].prev_hash)
                        if at is None:
                            raise ValueError(
                                "HEADERS reply does not link to the "
                                "known chain"
                            )
                        if at != len(headers) - 1:
                            for h in hashes[at + 1 :]:
                                del pos[h]
                            del headers[at + 1 :]
                            del hashes[at + 1 :]
                        for h in new:
                            if h.prev_hash != hashes[-1]:
                                raise ValueError(
                                    "HEADERS batch is not contiguous"
                                )
                            headers.append(h)
                            hashes.append(h.block_hash())
                            pos[hashes[-1]] = len(hashes) - 1
                        if len(headers) > max_headers:
                            raise ValueError(
                                f"peer served more than {max_headers} "
                                "headers"
                            )
            except (
                ConnectionError,
                OSError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,  # pre-3.11 spelling of the builtin
                TimeoutError,
            ) as e:
                # Stalled round or dead session — never a protocol
                # violation (those raise above).  Rotate to the next
                # target and resume from the headers already held.
                if sup.exhausted():
                    raise SyncStalled(
                        f"headers sync exhausted {attempts_max} failover "
                        f"attempts; last peer {t_host}:{t_port} ({e!r})"
                    ) from e
                delay = sup.record_stall()
                ti = (ti + 1) % len(targets)
                await asyncio.sleep(delay)

    return await asyncio.wait_for(_run(), timeout)


async def get_fees(
    host: str,
    port: int,
    difficulty: int,
    window: int = 0,
    timeout: float = 10.0,
    retarget=None,
) -> protocol.FeeStats:
    """Query confirmed-fee percentiles from the node at host:port — the
    wallet's price signal for `p1 tx --fee auto` (0 window = the node's
    default sample)."""

    async def _run() -> protocol.FeeStats:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getfees(window))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.FEES:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_status(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> dict:
    """Fetch a running node's full status JSON (`p1 status`) — height,
    peers, sync/storage health, and the overload block (governor state,
    admission drops, memory gauge).  Served even while the node sheds
    load, so the probe works exactly when an operator needs it most."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getstatus())
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.STATUS:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def maintain(
    host: str,
    port: int,
    command: dict,
    difficulty: int,
    timeout: float = 30.0,
    retarget=None,
) -> dict:
    """Drive a running node's maintenance plane (`p1 maintain`, v13):
    ``{"op": "status"|"rebase"|"prune"|"compact", ...}`` over
    GETMAINTAIN, returning the MAINTAIN reply — ``{"ok": bool, ...}``.
    A refused command comes back as ``{"ok": false, "error": ...}``:
    the zero-downtime contract means refusals are answers, never
    dropped sessions.  Kept reachable under SHED like GETSTATUS — an
    overloaded node must still accept the operation that relieves it.
    The default timeout is longer than the query probes': a re-base or
    compaction spills real bytes before answering."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(
                writer, protocol.encode_getmaintain(command)
            )
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.MAINTAIN:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_metrics(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> dict:
    """Fetch a node's (or a `p1 serve` replica's) telemetry registry
    snapshot (`p1 metrics`, v12): counters, gauges, and the per-stage
    latency histograms of node/telemetry.py.  Unlike GETSTATUS this
    probe is shed under overload — a refused scrape times out here and
    the caller retries later."""

    async def _run() -> dict:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getmetrics())
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.METRICS:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_account(
    host: str,
    port: int,
    account: str,
    difficulty: int,
    timeout: float = 10.0,
    retarget=None,
) -> protocol.AccountState:
    """Query ``account``'s consensus state (balance, nonce, next usable
    seq) from the node at host:port — what a wallet needs before signing.
    Skips unrelated frames the node pushes at handshake (e.g. its
    GETMEMPOOL request) until the ACCOUNT reply arrives."""

    async def _run() -> protocol.AccountState:
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(writer, protocol.encode_getaccount(account))
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.ACCOUNT:
                    return body

    return await asyncio.wait_for(_run(), timeout)


async def get_filters(
    host: str,
    port: int,
    start_height: int,
    count: int,
    difficulty: int,
    timeout: float = 30.0,
    retarget=None,
) -> list[tuple[bytes, bytes]]:
    """Fetch compact block filters for a main-chain height range: (block
    hash, filter bytes) pairs ascending from ``start_height``.  The
    server caps the range — fewer entries than asked means ask again
    from where the reply ended (or the chain ended there)."""

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):
            await protocol.write_frame(
                writer, protocol.encode_getfilters(start_height, count)
            )
            while True:
                mtype, body = await _read_msg(reader, writer)
                if mtype is MsgType.FILTERS:
                    start, entries = body
                    if start != start_height:
                        raise ValueError(
                            "FILTERS reply for a different start height"
                        )
                    return entries

    return await asyncio.wait_for(_run(), timeout)


async def get_snapshot(
    host: str,
    port: int,
    difficulty: int,
    timeout: float = 60.0,
    retarget=None,
    out_path=None,
):
    """Fetch the node's current state snapshot (chain/snapshot.py):
    manifest first, then chunk ranges, each chunk verified against its
    manifest digest AS IT ARRIVES and the state root checked at the end
    — the same incremental integrity contract the node's own snapshot
    boot applies.  Returns a fully verified ``LedgerSnapshot`` (or None
    when the peer serves no snapshot); ``out_path`` additionally writes
    the CRC-framed snapshot file.  The STATE is still only the serving
    peer's claim — only replaying the history proves it (the trust
    model `p1 snapshot` prints)."""
    from p1_tpu.chain import snapshot as chain_snapshot

    async def _run():
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):

            async def _reply():
                while True:
                    mtype, body = await _read_msg(reader, writer)
                    if mtype is MsgType.SNAPSHOT:
                        return body

            await protocol.write_frame(writer, protocol.encode_getsnapshot(0, 0))
            body = await _reply()
            if body[0] == "none":
                return None
            if body[0] != "manifest":
                raise ValueError("peer answered chunks before the manifest")
            manifest_payload = body[1]
            manifest = chain_snapshot.parse_manifest(manifest_payload)
            chunks: list[bytes] = []
            while len(chunks) < len(manifest.chunk_digests):
                await protocol.write_frame(
                    writer, protocol.encode_getsnapshot(len(chunks), 8)
                )
                body = await _reply()
                if body[0] != "chunks" or body[1] != len(chunks) or not body[2]:
                    raise ValueError("bad SNAPSHOT chunk range from peer")
                for payload in body[2]:
                    i = len(chunks)
                    if (
                        i >= len(manifest.chunk_digests)
                        or chain_snapshot.chunk_digest(payload)
                        != manifest.chunk_digests[i]
                    ):
                        raise ValueError(f"chunk {i} fails its manifest digest")
                    chunks.append(payload)
            snap = chain_snapshot.assemble(manifest, chunks)
            if out_path is not None:
                chain_snapshot.write_snapshot(out_path, manifest_payload, chunks)
            return snap

    return await asyncio.wait_for(_run(), timeout)


async def filter_scan(
    host: str,
    port: int,
    watch_items,
    difficulty: int,
    timeout: float = 120.0,
    retarget=None,
    fetch_blocks: bool = True,
    start_height: int = 1,
    page: int = 500,
):
    """Light-client sync by filter match (the round-9 serving plane's
    wallet flow): ONE session that

    1. syncs the peer's header chain (GETHEADERS locator rounds — the
       ~80 B/block skeleton),
    2. pages the compact filter stream (GETFILTERS) and matches
       ``watch_items`` (account ids as utf-8 bytes, and/or txids)
       locally — the peer never learns WHICH accounts the wallet
       watches, and the wallet asks zero per-address questions,
    3. fetches only the matching blocks (rare: the designed false-
       positive rate per absent item is ~1/M ≈ 1.3e-6) and pins each to
       the header chain by hash, dropping any filter false positives
       after inspection.

    Returns ``(headers, matches)`` where matches is a list of
    ``(height, block)`` — or ``(headers, [(height, block_hash), ...])``
    with ``fetch_blocks=False`` for callers that only want locations.
    Zero false negatives is the filter construction's guarantee
    (chain/filters.py): every block that actually touches a watched
    item IS in the matches (property-tested against full block scans).

    Trust model: same as ``get_headers`` — the header chain should be
    verified by the caller (``replay_host``); filters and blocks are
    pinned to it by hash, and fetched blocks are checked against their
    header's merkle commitment here, so a lying peer can omit service
    but cannot substitute content.
    """
    from p1_tpu.chain.chain import locator_hashes
    from p1_tpu.chain.filters import matches_any

    items = [
        it.encode("utf-8") if isinstance(it, str) else bytes(it)
        for it in watch_items
    ]

    async def _run():
        genesis = make_genesis(difficulty, retarget)
        headers = [genesis.header]
        hashes = [genesis.block_hash()]
        pos = {hashes[0]: 0}
        async with _session(host, port, difficulty, retarget) as (
            reader,
            writer,
            _,
        ):

            async def _reply(want):
                while True:
                    mtype, body = await _read_msg(reader, writer)
                    if mtype is want:
                        return body

            # 1. headers skeleton (single-session variant of get_headers;
            # the supervised multi-peer fetch lives there — this scan is
            # one wallet round against one chosen peer).
            while True:
                await protocol.write_frame(
                    writer, protocol.encode_getheaders(locator_hashes(hashes))
                )
                batch = await _reply(MsgType.HEADERS)
                new = [h for h in batch if h.block_hash() not in pos]
                if not new:
                    break
                at = pos.get(new[0].prev_hash)
                if at is None:
                    raise ValueError(
                        "HEADERS reply does not link to the known chain"
                    )
                if at != len(headers) - 1:
                    for h in hashes[at + 1 :]:
                        del pos[h]
                    del headers[at + 1 :]
                    del hashes[at + 1 :]
                for h in new:
                    if h.prev_hash != hashes[-1]:
                        raise ValueError("HEADERS batch is not contiguous")
                    headers.append(h)
                    hashes.append(h.block_hash())
                    pos[hashes[-1]] = len(hashes) - 1

            # 2. filter stream + local match.
            matched: list[tuple[int, bytes]] = []
            h = max(1, start_height)
            while h < len(hashes):
                await protocol.write_frame(
                    writer,
                    protocol.encode_getfilters(
                        h, min(page, len(hashes) - h)
                    ),
                )
                start, entries = await _reply(MsgType.FILTERS)
                if not entries:
                    break
                for i, (bhash, fbytes) in enumerate(entries):
                    height = start + i
                    if height >= len(hashes):
                        break  # peer's chain ran ahead of our skeleton
                    if bhash != hashes[height]:
                        # The peer reorged between the header sync and
                        # this page; the stale tail's filters are for
                        # blocks we did not pin — stop at the divergence
                        # (a fuller client would re-sync headers).
                        break
                    if items and matches_any(fbytes, bhash, items):
                        matched.append((height, bhash))
                h = start + len(entries)

            if not fetch_blocks:
                return headers, matched

            # 3. fetch ONLY the matched blocks, pinned by hash; drop
            # false positives after inspection (a block whose filter
            # matched but that touches none of the watched items).
            out = []
            for height, bhash in matched:
                await protocol.write_frame(
                    writer,
                    protocol.encode_getblocks([hashes[height - 1]]),
                )
                blocks = await _reply(MsgType.BLOCKS)
                if not blocks or blocks[0].block_hash() != bhash:
                    raise ValueError(
                        "peer did not serve the filter-matched block"
                    )
                block = blocks[0]
                if not block.merkle_ok():
                    raise ValueError(
                        "matched block fails its merkle commitment"
                    )
                touched = set()
                for tx in block.txs:
                    touched.add(tx.txid())
                    touched.add(tx.sender.encode("utf-8"))
                    touched.add(tx.recipient.encode("utf-8"))
                if any(it in touched for it in items):
                    out.append((height, block))
            return headers, out

    return await asyncio.wait_for(_run(), timeout)
